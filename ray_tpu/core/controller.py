"""Controller — head process combining the reference's GCS + raylet roles.

Reference analogs:
  * cluster/actor/PG/object directories — GCS (`src/ray/gcs/gcs_server`),
    whose hot tables are INDEPENDENT SHARDED TABLES — mirrored here by
    `control_shards.py`
  * task queueing, dispatch, worker pool  — raylet (`src/ray/raylet/node_manager.cc`,
    `worker_pool.h:156`, `local_task_manager.cc`)
  * object lifetime/spill — `LocalObjectManager` + plasma eviction

Redesign rationale (TPU-first): ONE head process, MANY event loops. The hot
actor/lease/worker directories are partitioned by ID hash into N shards
(`controller_shards`, crc32 % N); each shard's own event loop is the single
writer for its actors' delivery plane (send queues, pumps, inflight maps),
so a 2,000-actor wave's per-call bookkeeping never serializes behind the
scheduler. The MAIN loop keeps what is inherently global: scheduling +
node capacity, the object directory, placement groups, and the thin
cross-shard coordination layer (named-actor registry, FT snapshots,
timeline). Cross-loop traffic is marshaled, never locked-and-shared — see
docs/SHARDED_CONTROL_PLANE.md for the ownership rules and invariants.
The multi-node seam is unchanged: remote node daemons join through
`register_node`, keeping scheduler state per-node the way
`ClusterResourceManager` does.

Data plane stays OUT of this process: objects ride named shm segments
(store.py); the controller holds only locations, sizes, refstate, and waiters.

Fault model (docs/CONTROL_PLANE_HA.md): head death is a recoverable event,
not a cluster funeral. Every state-mutating transition is written ahead to
a CRC-guarded, fsync-batched event log (event_log.py); the periodic
checkpoint compacts it. A restarted head restores checkpoint + replay,
re-binds its port, and re-adopts surviving workers/agents as they
reconnect — actors, the data plane, and in-flight direct calls never
touch the head on their hot paths and keep running through the outage.
"""

from __future__ import annotations

import asyncio
import itertools
import os
import subprocess
import sys
import time
import traceback
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

import cloudpickle

from . import serialization, store
from .exceptions import (
    ActorDiedError,
    TaskCancelledError,
    TaskError,
    WorkerCrashedError,
)
from . import config as rt_config
from .rpc import Connection, read_msg
from .rpc import auth_token as rpc_auth_token, open_rpc_connection
from .ids import ObjectID
from .task_spec import (
    spec_from_proto_bytes,
    spec_to_proto_bytes,
    DefaultSchedulingStrategy,
    NodeAffinitySchedulingStrategy,
    NodeLabelSchedulingStrategy,
    PlacementGroupSchedulingStrategy,
    SpreadSchedulingStrategy,
    TaskSpec,
    TaskType,
)

IDLE = "idle"
BUSY = "busy"
STARTING = "starting"
ACTOR = "actor"
DEAD = "dead"

# ---------------------------------------------- prometheus exposition utils
# Hoisted to module level: compiled ONCE, not re-imported/recompiled on
# every /metrics scrape.
import re as _re  # noqa: E402

_METRIC_NAME_RE = _re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_KEY_RE = _re.compile(r"[^a-zA-Z0-9_]")


def _san_name(name: str) -> str:
    return _METRIC_NAME_RE.sub("_", name)


def _esc_label(v) -> str:  # prometheus exposition label-value escaping
    return str(v).replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _esc_help(v) -> str:  # HELP lines escape backslash + newline only
    return str(v).replace("\\", r"\\").replace("\n", r"\n")


def _format_tags(tags) -> str:
    return ",".join(
        f'{_LABEL_KEY_RE.sub("_", k)}="{_esc_label(v)}"' for k, v in tags
    )


def _format_le(b: float) -> str:
    # 0.25 -> "0.25", 1.0 -> "1.0" (float repr is stable and scrape-safe)
    return repr(float(b))
# Leased to a submitter for the direct task path (reference: worker leases,
# `direct_task_transport.cc:135` — steady-state submissions bypass the
# scheduler entirely; the controller only grants/returns the lease).
LEASED = "leased"


HEAD_NODE = "node0"


def _task_env_key(spec) -> str:
    """Isolation hash of the task's runtime_env ("" = plain pool)."""
    renv = spec.options.runtime_env
    if not renv:
        return ""
    from ..runtime_env.isolation import isolation_key

    return isolation_key(renv)


@dataclass
class WorkerState:
    worker_id: str
    conn: Optional[Connection] = None
    pid: int = 0
    state: str = STARTING
    current_task: Optional[str] = None  # task hex
    actor_hex: Optional[str] = None
    assigned: Dict[str, float] = field(default_factory=dict)
    # When set, `assigned` was carved from this PG bundle, not node capacity.
    assigned_pg: Optional[Tuple[str, int]] = None
    # Lease reuse (reference: cached leases, `direct_task_transport.cc:135`):
    # one same-shape argless task queued BEHIND current_task on this worker,
    # promoted at task_done without a scheduler round trip.
    prefetch_task: Optional[str] = None
    # Task hex a reclaim push is in flight for (see
    # _reclaim_stranded_prefetches) — suppresses duplicate reclaims; cleared
    # by the worker's task_dropped push or by task_done (reclaim lost).
    reclaiming_task: Optional[str] = None
    blocked: bool = False
    node_id: str = HEAD_NODE
    has_tpu: bool = False
    # Isolation hash (runtime_env conda/container — `isolation_key`): tasks
    # only dispatch onto workers whose env_key matches; "" = plain pool.
    env_key: str = ""
    # Direct task plane: the worker's own listener for submitter→worker
    # pushes (reference: core-worker gRPC server for PushNormalTask).
    direct_addr: str = ""
    # conn_id of the lease holder while state == LEASED.
    leased_to: Optional[int] = None
    # A revoke push is in flight to the lease holder.
    revoking: bool = False
    # Killed by the memory monitor — labels the death error as OOM.
    oom_killed: bool = False


@dataclass
class NodeState:
    """Per-node view (reference analog: `NodeResources` in
    `cluster_resource_data.h:289` + the GCS node directory). The head node
    (`node0`) is the controller's own machine slice — `conn is None`; remote
    nodes are `node_agent.py` daemons."""

    node_id: str
    conn: Optional[Connection] = None
    fetch_addr: str = ""
    bulk_addr: str = ""
    # Two-level scheduling (reference: ClusterTaskManager picks the node,
    # LocalTaskManager owns the local queue + worker grant —
    # `scheduling/cluster_task_manager.h:42` / `local_task_manager.cc:1`):
    # agents that run a LocalDispatcher accept queued-task handoffs and keep
    # dispatching them to leased local workers with no head involvement.
    dispatch: bool = False
    handoff_inflight: int = 0
    total: Dict[str, float] = field(default_factory=dict)
    available: Dict[str, float] = field(default_factory=dict)
    session_tag: str = ""
    alive: bool = True
    spawning: int = 0
    spawning_tpu: int = 0
    object_store_memory: int = 0
    # Node labels (reference: `NodeLabelSchedulingStrategy` label matching).
    labels: Dict[str, str] = field(default_factory=dict)
    # Last time resources were acquired/released here — drives the
    # autoscaler's idle-node detection (reference: `LoadMetrics`
    # `load_metrics.py:63` last_used_time_by_ip).
    last_active: float = field(default_factory=time.monotonic)
    # Latest cpu/mem/disk/TPU sample (reference: reporter_agent node stats).
    sys_metrics: Dict[str, float] = field(default_factory=dict)
    # Worker ids the node's agent spawned whose process is currently alive
    # (from health-probe replies) — the controller's only liveness signal
    # for agent-spawned isolated workers it has no proc handle for.
    agent_alive_workers: set = field(default_factory=set)

    def utilization(self) -> float:
        fracs = [
            1.0 - self.available.get(k, 0.0) / v
            for k, v in self.total.items()
            if v > 0
        ]
        return max(fracs) if fracs else 0.0


@dataclass
class ObjectState:
    status: str = "pending"  # pending | ready
    inline: Optional[bytes] = None
    # node_id -> shm name on that node (primary + pulled copies).
    locations: Dict[str, str] = field(default_factory=dict)
    spilled_path: Optional[str] = None
    spilled_node: str = HEAD_NODE
    size: int = 0
    last_access: float = 0.0
    events: List[asyncio.Event] = field(default_factory=list)
    # Tasks blocked on this object (by task hex).
    dependents: Set[str] = field(default_factory=set)
    # --- distributed refcount (reference: `reference_count.h:39-52`) ---
    holders: Set[int] = field(default_factory=set)  # conn ids with live refs
    ever_held: bool = False
    pinned: int = 0          # queued/running tasks using this as an arg
    recon_attempts: int = 0  # lineage re-executions tried for this object
    expected: bool = False   # a submitted task will produce this (return id)
    gc_at: float = 0.0       # earliest sweep time once GC-eligible
    # ObjectRefs nested in this value's bytes: pinned for the container's
    # lifetime (reference: `ReferenceCounter::AddNestedObjectIds`).
    contains: List[str] = field(default_factory=list)

    @property
    def shm_name(self) -> Optional[str]:  # head-node name (spill path compat)
        return self.locations.get(HEAD_NODE)

    def is_lost(self) -> bool:
        return (
            self.status == "ready"
            and self.inline is None
            and not self.locations
            and self.spilled_path is None
        )


class _HandoffFence:
    """Direct-channel switch marker riding the actor send queue — duck-typed
    to the TaskSpec fields the queue paths read (drain/unpin are no-ops)."""

    __slots__ = ("token", "arg_refs", "return_ids", "num_returns", "name")

    def __init__(self, token: str):
        self.token = token
        self.arg_refs = []
        self.return_ids = []
        self.num_returns = 0
        self.name = "__handoff_fence__"


@dataclass
class ActorState:
    actor_hex: str
    spec: Optional[TaskSpec] = None  # creation spec kept for restarts
    worker_id: Optional[str] = None
    state: str = "pending"  # pending | alive | restarting | dead
    name: str = ""
    namespace: str = "default"
    handle_bytes: bytes = b""
    restarts_used: int = 0
    # Submission-ordered calls not yet delivered to the worker. A single pump
    # coroutine drains this FIFO so per-actor call order is preserved even
    # when some calls wait on unready args (reference analog: the ordered
    # `ActorSchedulingQueue`). OWNED BY THE ACTOR'S SHARD LOOP: appends and
    # pops are marshaled there (control_shards.py ownership rules).
    send_queue: deque = field(default_factory=deque)
    # Calls delivered to the worker and not yet completed: task hex -> spec.
    # Written by the shard pump, popped by main-loop completion handlers —
    # multi-step sequences take `lock`.
    inflight: Dict[str, TaskSpec] = field(default_factory=dict)
    pump_active: bool = False
    # Awaited on the shard loop; main-loop state transitions wake it via
    # wake() (cross-loop marshal).
    state_event: asyncio.Event = field(default_factory=asyncio.Event)
    detached: bool = False
    init_error: Optional[TaskError] = None
    # Owning shard (set at insert; None only in unit tests that poke state).
    shard: Any = None
    lock: Any = field(default_factory=__import__("threading").Lock)

    def wake(self):
        """Wake a pump blocked on state_event, from any thread."""
        if self.shard is not None and self.shard.loop is not None:
            try:
                self.shard.loop.call_soon_threadsafe(self.state_event.set)
                return
            except RuntimeError:
                pass  # shard loop stopped (shutdown)
        self.state_event.set()


@dataclass
class PendingTask:
    spec: TaskSpec
    deps_remaining: Set[str] = field(default_factory=set)
    retries_left: int = 0
    # Spread/affinity commitment: once a node is chosen, later scheduling
    # passes honor it (otherwise the round-robin re-rolls every pass and the
    # task bounces between half-spawned nodes).
    pinned_node: Optional[str] = None
    # Cached (demand, strategy) signature for the scheduler's no-capacity
    # fast path — building it per scan entry per pass dominated deep-queue
    # profiles (1.6M sorted() calls per 3k tasks). Invalidated when
    # pinned_node changes (it is part of the signature).
    _sig_cache: Optional[tuple] = None
    _sig_pinned: Optional[str] = None

    def sched_sig(self, need_tpu: bool):
        strat = self.spec.options.scheduling_strategy
        if isinstance(strat, SpreadSchedulingStrategy):
            return None  # rotation → per-decision outcomes; never fast-path
        if self._sig_cache is None or self._sig_pinned != self.pinned_node:
            self._sig_cache = (
                tuple(sorted(self.spec.resources.items())),
                type(strat).__name__,
                getattr(strat, "node_id", None),
                getattr(strat, "soft", None),
                tuple(sorted(getattr(strat, "hard", {}).items())),
                need_tpu,
                self.pinned_node,
            )
            self._sig_pinned = self.pinned_node
        return self._sig_cache


class Controller:
    def __init__(
        self,
        num_cpus: float,
        resources: Dict[str, float],
        session_dir: str,
        object_store_memory: Optional[int] = None,
        port: int = 0,
        standalone: bool = False,
    ):
        # standalone: a Cluster-managed controller outlives its drivers
        # (sessions auto-started by ray_tpu.init still die with the driver).
        self.standalone = standalone
        self.session_dir = session_dir
        os.makedirs(session_dir, exist_ok=True)
        self.spill_dir = os.path.join(session_dir, "spill")
        self.port = port
        self.object_store_memory = object_store_memory or int(
            min(
                rt_config.get("object_store_fraction")
                * os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES"),
                64 << 30,
            )
        )
        self.store_bytes_used = 0
        self.local_store = store.LocalStore()

        head_total = {"CPU": float(num_cpus), **resources}
        self.head = NodeState(
            node_id=HEAD_NODE,
            total=dict(head_total),
            available=dict(head_total),
            object_store_memory=self.object_store_memory,
        )
        self.nodes: Dict[str, NodeState] = {HEAD_NODE: self.head}
        # In-flight cross-node pulls, deduped: (node_id, object_hex) -> Future.
        self._pulls: Dict[Tuple[str, str], asyncio.Future] = {}
        # Broadcast shaping: active pulls served per source node + waiters
        # parked until a pull completes (new copies appear).
        self._src_active: Dict[str, int] = {}
        self._transfer_waiters: List[asyncio.Future] = []
        # (node_id, started_at, tpu) per in-flight spawn — boot-budget
        # expiry for spawns that die before registering.
        self._spawn_ledger: List[tuple] = []
        # Controller -> agent fetch-server connections (for pulls INTO node0).
        self._fetch_conns: Dict[str, Connection] = {}
        self._spread_rr = 0

        # Lineage: creating TaskSpec per task, enabling lost-object
        # re-execution (reference: `ObjectRecoveryManager::RecoverObject`,
        # `object_recovery_manager.cc:22`; ObjectID encodes TaskID so the
        # lookup is free — `common/id.h:272` property kept by ids.py).
        self.lineage: Dict[str, TaskSpec] = {}
        self._lineage_cap = rt_config.get("lineage_cap")
        self._conn_counter = itertools.count(1)
        # conn_id → live Connection (lease revocation pushes to holders).
        self._conns_by_id: Dict[int, Connection] = {}
        # Direct actor-call handoff fences (h_actor_handoff).
        self._handoff_counter = itertools.count(1)
        self._handoff_waiters: Dict[str, asyncio.Future] = {}
        # Unsatisfied lease requests → autoscaler demand (expires in 5s).
        self._lease_backlog: Dict[tuple, tuple] = {}
        # Worker ids currently LEASED — lets the backlog revoke sweep touch
        # only lease holders instead of scanning the whole worker table
        # every pass (O(W·passes) measured on actor waves).
        self._leased_ids: Set[str] = set()
        # Worker ids with a prefetched task queued (same pattern: the
        # stranded-prefetch sweep is per-pass; self-cleaning against
        # ws.prefetch_task, so a missed clear is harmless).
        self._prefetch_ids: Set[str] = set()
        # Pulsed on every worker registration — parked lease requests and
        # other capacity waiters re-check on it.
        self._worker_arrival = asyncio.Event()
        # Direct tasks currently executing, reported via batched task_events
        # (observability only — the scheduler never touches these).
        self.direct_running: Dict[str, dict] = {}
        self._gc_candidates: Set[str] = set()
        # Reverse index: conn_id -> hex ids it holds (O(refs) disconnects).
        self._conn_refs: Dict[int, Set[str]] = {}
        # (name, tags) -> (value, kind, last_update_ts) — user scalar metrics
        # for /metrics; (name, tags) -> dict for histogram families. Series
        # idle past _metric_staleness_s are dropped at scrape time (gauges
        # from dead replicas/workers must not persist forever).
        self.user_metrics: Dict[Tuple[str, tuple], Tuple[float, str, float]] = {}
        self.user_hists: Dict[Tuple[str, tuple], dict] = {}
        self.user_metric_help: Dict[str, str] = {}
        self._metric_staleness_s = float(
            os.environ.get("RAY_TPU_METRIC_STALENESS_S", 900.0)
        )
        self.metrics_port = 0
        self._metrics_server: Optional[asyncio.base_events.Server] = None

        self.objects: Dict[str, ObjectState] = {}
        # Hot directories, partitioned by ID hash into independent shards
        # (control_shards.py — the GCS-table split): each shard's event
        # loop owns its actors' delivery plane; the tables themselves are
        # structurally mutated only on this (main) loop.
        from .control_shards import ControlShard, ShardedDict

        n_shards = max(1, int(rt_config.get("controller_shards")))
        threaded = bool(rt_config.get("controller_shard_threads"))
        self.shards = [ControlShard(i, threaded=threaded) for i in range(n_shards)]
        self.workers: "ShardedDict" = ShardedDict(self.shards, "workers")
        self.jobs: Dict[str, dict] = {}
        self.streams: Dict[str, dict] = {}  # streaming-generator progress
        self._spec_blobs: Dict[str, bytes] = {}  # snapshot pickle cache
        self.actors: "ShardedDict" = ShardedDict(self.shards, "actors")
        # Cross-shard coordination state (main-loop-owned): the name
        # registry spans shards — exactly one (namespace, name) → one
        # actor in one shard.
        self.named_actors: Dict[Tuple[str, str], str] = {}
        self.pgs: Dict[str, dict] = {}
        self.ready_queue: deque = deque()  # PendingTask with no deps
        self.waiting_tasks: Dict[str, PendingTask] = {}  # task hex -> waiting on deps
        self.running: Dict[str, Tuple[str, PendingTask]] = {}  # task hex -> (worker, pt)
        self.cancelled: Set[str] = set()
        # Explicit capacity requests from `autoscaler.sdk.request_resources`
        # (reference: `python/ray/autoscaler/sdk` → GCS resource_request).
        self._explicit_demands: List[Dict[str, float]] = []
        self.timeline: List[dict] = []
        # Absolute index of timeline[0] — lets poll_events cursors survive
        # truncation (a cursor is "events seen so far", not a list index).
        self._timeline_base = 0
        self.drivers: Set[Connection] = set()
        self._worker_counter = itertools.count()
        # Isolated-worker bookkeeping (runtime_env conda/container):
        # worker_id -> env_key applied at registration; (node, key) ->
        # last spawn time (a monotonic gate so one isolated worker boots
        # per key per node at a time, self-healing if the spawn dies).
        self._worker_env_keys: Dict[str, str] = {}
        # (node, key) -> (last spawn time, worker_id of that attempt)
        self._iso_booting: Dict[Tuple[str, str], Tuple[float, str]] = {}
        # (node, key) -> consecutive spawns that died before registering
        # (wrapper exec'd fine but the env is broken: bad conda env name,
        # unpullable image, ...). Capped — see _spawn_isolated.
        self._iso_attempts: Dict[Tuple[str, str], int] = {}
        # (node_id, env_key) -> error: the isolation binary is missing on
        # that node (sticky; a node gaining conda mid-session must rejoin).
        self._iso_unavailable: Dict[Tuple[str, str], str] = {}
        self._max_workers = max(int(num_cpus) * rt_config.get("max_workers_per_cpu"), 8)
        self._min_workers = 2
        self._server: Optional[asyncio.base_events.Server] = None
        self._scheduling = False
        self._schedule_again = False
        # Deferred-scheduling coalescing: _schedule() marks a pass pending
        # and runs it once per event-loop drain (see _schedule_tick) — a
        # 2,000-worker registration storm triggers a handful of passes
        # instead of one full pass per message (r6: 1,564 passes for a
        # 300-actor wave, ~2s of pure pass overhead).
        self._schedule_soon = False
        self._shutdown_event = asyncio.Event()
        self._worker_procs: Dict[str, subprocess.Popen] = {}
        self._forkserver = None  # set in start()
        # Write-ahead event log (event_log.py): every state-mutating
        # transition appends; restore = checkpoint + replay. None when
        # disabled (driver-owned session / memory:// backend).
        self._wal = None
        self._recoveries_total = 0

    # ------------------------------------------------------------ lifecycle
    _SNAPSHOT_KEY = "controller_state"

    @property
    def _gcs_store(self):
        """Pluggable metadata backend (reference: `src/ray/gcs/store_client`
        — InMemory vs Redis). memory:// disables controller FT; file://
        (default, session dir) survives kill -9; a shared filesystem gives
        off-box durability in Redis's role."""
        if getattr(self, "_gcs_store_client", None) is None:
            from .store_client import make_store_client

            self._gcs_store_client = make_store_client(
                rt_config.get("gcs_storage"), self.session_dir
            )
        return self._gcs_store_client

    async def start(self, restore: bool = False):
        # Shard plumbing: inline shards execute on this loop; threaded
        # shards already run their own (control_shards.py).
        self._main_loop = asyncio.get_running_loop()
        for sh in self.shards:
            sh.attach_main_loop(self._main_loop)
        self._open_wal()
        # _restore_state handles missing/corrupt state itself — checkpoint
        # read (if any) + WAL replay past it.
        restored = restore
        if restored:
            t0 = time.monotonic()
            restored = self._restore_state()  # adopts the dead session's tag
            if restored:
                self._recoveries_total += 1
                self._self_inc("controller_recoveries_total", 1.0)
                self._self_observe(
                    "controller_recovery_seconds", time.monotonic() - t0
                )
        if not restored:
            store.set_session_tag(str(os.getpid()))
            store.cleanup_stale_segments()
            # Native arena (plasma-equivalent): the controller owns the
            # segment; drivers/workers attach after the session-tag handshake.
            self.local_store = store.make_store(
                create_arena=True, arena_capacity=self.object_store_memory
            )
        # Real-host networking (reference: node_ip_address plumbing,
        # `services.py:295-305`): advertise node_ip, listen on bind_address.
        self.node_ip = rt_config.get("node_ip")
        bind = rt_config.get("bind_address") or self.node_ip
        self._server = await asyncio.start_server(
            self._on_connection, host=bind, port=self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        if not restored:
            if self._wal is not None:
                # Fresh session over this dir: discard any surviving log
                # (e.g. a failed restore's rolled-back records) — appending
                # this session's boot AFTER them would make the next
                # failover replay the dead session's actors as ours.
                self._wal.reset()
            # First durable record: enough identity (tag/port/arena size) to
            # restore from a BARE log — a crash before the first checkpoint
            # must still recover everything appended so far.
            self._wal_append(
                "controller_boot",
                session_tag=store.SESSION_TAG,
                port=self.port,
                object_store_memory=self.object_store_memory,
            )
        # Head-store bulk plane (bulk.py): serves the controller's objects to
        # pulling agents the same way agents serve each other.
        from .bulk import BulkServer

        self._bulk_server = BulkServer(self.local_store, bind_host=bind)
        self._bulk_addr = f"{self.node_ip}:{self._bulk_server.start()}"
        # Warm-worker template (forkserver.py): pays the interpreter+import
        # cost once; CPU workers fork from it in ~10 ms once it is ready.
        from .forkserver import ForkServerClient

        self._forkserver = ForkServerClient(self.session_dir, "head")
        if rt_config.get("worker_forkserver"):
            self._forkserver.start()
        # Prometheus exposition (reference: `metrics_agent.py:83-95`).
        self._metrics_server = await asyncio.start_server(
            self._on_metrics_connection, host=bind, port=0
        )
        self.metrics_port = self._metrics_server.sockets[0].getsockname()[1]
        # Dashboard (reference: `dashboard/head.py`; here an in-process HTTP
        # server over the same state the state API serves).
        self.dashboard = None
        if rt_config.get("dashboard"):
            # Observability must never be fatal to the cluster: a taken port
            # (second cluster, stale process) degrades to no dashboard.
            try:
                from ..dashboard import DashboardServer

                self.dashboard = DashboardServer(self)
                await self.dashboard.start(rt_config.get("dashboard_port"))
            except OSError as e:
                print(f"dashboard disabled: {e}", file=sys.stderr)
                self.dashboard = None
        self._write_session_info()
        if self.standalone:
            store.mark_restorable(store.SESSION_TAG, True)
        if not restored:
            for _ in range(self._min_workers):
                self._spawn_worker()
        asyncio.ensure_future(self._gc_loop())
        asyncio.ensure_future(self._snapshot_loop())
        asyncio.ensure_future(self._health_check_loop())
        asyncio.ensure_future(self._head_memory_monitor_loop())

    # --------------------------------------------------- persistence (GCS FT)
    # Reference analog: GCS tables behind `RedisStoreClient`
    # (`redis_store_client.h:33`) + replay via `gcs_init_data.cc`. Redesign:
    # a WRITE-AHEAD EVENT LOG (event_log.py) appends every state-mutating
    # transition as it happens; the periodic pickle of the durable
    # directories is now a CHECKPOINT that compacts the log (snapshot =
    # checkpoint + truncate-before). A restarted controller restores the
    # checkpoint, REPLAYS the log past it, re-binds the SAME port, and
    # re-adopts workers as they reconnect (their shm arena survived the
    # crash — kill -9 skips teardown, and segment names key off the
    # ORIGINAL session tag). Recovery loses nothing after the last WAL
    # fsync instead of everything after the last snapshot tick. See
    # docs/CONTROL_PLANE_HA.md for the record schema and recovery ordering.
    def _open_wal(self):
        """The WAL is active exactly where restore is possible: standalone
        controllers (driver-owned sessions die with their driver) with a
        durable metadata backend."""
        if not self.standalone or not rt_config.get("wal_enabled"):
            return
        if str(rt_config.get("gcs_storage")).startswith("memory"):
            return
        from .event_log import EventLog

        self._wal = EventLog(
            os.path.join(self.session_dir, "wal"),
            segment_bytes=rt_config.get("wal_segment_bytes"),
            sync=rt_config.get("wal_sync"),
            fsync_interval_s=rt_config.get("wal_fsync_interval_s"),
            fsync_bytes=rt_config.get("wal_fsync_bytes"),
            on_fsync=self._on_wal_fsync,
        )
        if self._wal.truncated_records:
            # Torn tail cut at open: the dropped bytes were never
            # acknowledged durable, but leave a forensic marker.
            self._event(
                "recovery_truncated", records=self._wal.truncated_records
            )

    def _wal_append(self, kind: str, **fields):
        if self._wal is not None:
            self._wal.append(kind, fields)

    def _on_wal_fsync(self, seconds: float):
        # Fires on the WAL flusher THREAD — marshal onto the main loop (the
        # metric dicts are main-loop-owned, and /metrics iterates them).
        loop = getattr(self, "_main_loop", None)
        if loop is not None:
            try:
                loop.call_soon_threadsafe(
                    self._self_observe, "controller_log_fsync_seconds", seconds
                )
            except RuntimeError:
                pass  # loop closed (shutdown)

    # Controller-internal metric feeds: same aggregation shapes as
    # h_record_metric, but written locally (the controller has no client
    # backend to push through). Families/boundaries live in util/metrics.py
    # so tests and dashboards share one definition.
    def _self_inc(self, name: str, value: float):
        key = (name, ())
        from ..util.metrics import CONTROLLER_HA_HELP

        self.user_metric_help.setdefault(name, CONTROLLER_HA_HELP.get(name, ""))
        cur = self.user_metrics.get(key, (0.0, None, 0.0))[0]
        self.user_metrics[key] = (cur + value, "counter", time.time())

    def _self_set_gauge(self, name: str, value: float):
        from ..util.metrics import CONTROLLER_HA_HELP

        self.user_metric_help.setdefault(name, CONTROLLER_HA_HELP.get(name, ""))
        self.user_metrics[(name, ())] = (value, "gauge", time.time())

    def _self_observe(self, name: str, value: float):
        from ..util.metrics import CONTROLLER_HA_BOUNDARIES, CONTROLLER_HA_HELP

        boundaries = CONTROLLER_HA_BOUNDARIES[name]
        self.user_metric_help.setdefault(name, CONTROLLER_HA_HELP.get(name, ""))
        key = (name, ())
        h = self.user_hists.get(key)
        if h is None:
            h = self.user_hists[key] = {
                "boundaries": boundaries,
                "buckets": [0] * (len(boundaries) + 1),
                "sum": 0.0, "count": 0,
            }
        idx = 0
        while idx < len(boundaries) and value > boundaries[idx]:
            idx += 1
        h["buckets"][idx] += 1
        h["sum"] += float(value)
        h["count"] += 1
        h["ts"] = time.time()

    def _spec_blob(self, actor_hex: str, spec) -> Optional[bytes]:
        """Specs are immutable — pickle once, not on every snapshot tick."""
        if spec is None:
            return None
        blob = self._spec_blobs.get(actor_hex)
        if blob is None:
            blob = self._spec_blobs[actor_hex] = cloudpickle.dumps(spec)
        return blob

    def _snapshot_state(self) -> dict:
        from .control_shards import HASH_NAME

        return {
            "session_tag": store.SESSION_TAG,
            # WAL position this checkpoint covers: restore replays records
            # AFTER this seq; compaction unlinks segments at or below it.
            "wal_seq": self._wal.seq if self._wal is not None else 0,
            # Shard layout at snapshot time (forensics + the FT test's
            # cross-shard invariant: the per-shard id lists are disjoint and
            # their union is exactly the actor table). Restore re-routes by
            # the restoring controller's OWN layout, so this is a record,
            # not a constraint.
            "shard_layout": {
                "n": len(self.shards),
                "hash": HASH_NAME,
                "actor_shards": [sorted(sh.actors) for sh in self.shards],
                "worker_shards": [sorted(sh.workers) for sh in self.shards],
            },
            "port": self.port,
            "object_store_memory": self.object_store_memory,
            "store_bytes_used": self.store_bytes_used,
            "named_actors": dict(self.named_actors),
            "jobs": {
                jid: {k: j[k] for k in
                      ("pid", "entrypoint", "status", "log_path",
                       "start_time", "end_time")}
                for jid, j in self.jobs.items()
            },
            "actors": {
                h: {
                    "spec": self._spec_blob(h, a.spec),
                    "name": a.name,
                    "namespace": a.namespace,
                    "handle_bytes": a.handle_bytes,
                    "state": a.state,
                    "worker_id": a.worker_id,
                    "restarts_used": a.restarts_used,
                    "detached": a.detached,
                }
                for h, a in self.actors.items()
            },
            "pgs": {k: dict(v) for k, v in self.pgs.items()},
            "objects": {
                h: {
                    "status": o.status,
                    "inline": o.inline,
                    "locations": dict(o.locations),
                    "spilled_path": o.spilled_path,
                    "spilled_node": o.spilled_node,
                    "size": o.size,
                    "ever_held": o.ever_held,
                    "expected": o.expected,
                    "contains": list(o.contains),
                }
                for h, o in self.objects.items()
                if o.status == "ready"
            },
        }

    async def _snapshot_loop(self):
        # Driver-owned sessions (non-standalone) die with their driver and
        # can never restore — don't pay the checkpoint cost for them.
        if not self.standalone:
            return
        loop = asyncio.get_running_loop()

        def dump(state: dict):
            self._gcs_store.put(self._SNAPSHOT_KEY, cloudpickle.dumps(state))

        while not self._shutdown_event.is_set():
            await asyncio.sleep(rt_config.get("snapshot_interval_s"))
            try:
                # Build the (shallow-copied) state on-loop, serialize + write
                # OFF-loop — large tables must not stall scheduling/RPC.
                state = self._snapshot_state()
                await loop.run_in_executor(None, dump, state)
                # Checkpoint landed: compact the log (truncate-before). The
                # durability boundary is the WAL fsync, not this tick.
                if self._wal is not None:
                    self._wal.checkpoint(state["wal_seq"])
                    self._self_set_gauge(
                        "controller_log_bytes", float(self._wal.total_bytes())
                    )
            except Exception:  # noqa: BLE001
                traceback.print_exc()

    def _restore_state(self) -> bool:
        """Checkpoint restore + WAL replay. Either alone is sufficient: a
        bare log (crash before the first checkpoint) replays from its
        controller_boot record; a bare checkpoint (WAL disabled) restores
        exactly the old snapshot semantics."""
        snap = None
        try:
            snap = cloudpickle.loads(self._gcs_store.get(self._SNAPSHOT_KEY))
        except Exception:  # noqa: BLE001 — missing/corrupt checkpoint: the
            # WAL replay below may still carry the full state; a corrupt
            # checkpoint with no WAL is a fresh start (marked in the
            # timeline once the controller is up).
            snap = None
        wal_seq = 0
        identity = False  # session_tag/port adopted from SOME durable source
        if snap is not None:
            wal_seq = int(snap.get("wal_seq", 0))
            store.set_session_tag(snap["session_tag"])
            self.port = snap["port"]
            self.object_store_memory = snap["object_store_memory"]
            self.store_bytes_used = snap.get("store_bytes_used", 0)
            identity = True
            self.named_actors = dict(snap["named_actors"])
            for jid, j in snap.get("jobs", {}).items():
                self.jobs[jid] = {**j, "proc": None}  # re-adopted by pid
            for h, a in snap["actors"].items():
                astate = ActorState(
                    actor_hex=h,
                    spec=cloudpickle.loads(a["spec"]) if a["spec"] else None,
                    name=a["name"],
                    namespace=a["namespace"],
                    handle_bytes=a["handle_bytes"],
                    detached=a["detached"],
                )
                astate.restarts_used = a["restarts_used"]
                astate.worker_id = a["worker_id"]
                # Until its worker reconnects, the actor is "restarting":
                # calls queue instead of failing (reference: restart states).
                astate.state = "restarting" if a["state"] in ("alive", "pending", "restarting") else a["state"]
                # Insertion re-routes by the CURRENT shard layout — a restore
                # with a different controller_shards repartitions cleanly.
                self.actors[h] = astate
                astate.shard = self.actors.shard_for(h)
            for k, v in snap["pgs"].items():
                self.pgs[k] = dict(v)
            for h, o in snap["objects"].items():
                obj = self._obj(h)
                obj.status = o["status"]
                obj.inline = o["inline"]
                obj.locations = dict(o["locations"])
                obj.spilled_path = o["spilled_path"]
                obj.spilled_node = o["spilled_node"]
                obj.size = o["size"]
                obj.ever_held = o["ever_held"]
                obj.expected = o["expected"]
                obj.contains = list(o["contains"])
                for c in obj.contains:
                    self._obj(c).pinned += 1
        replayed = 0
        if self._wal is not None:
            for seq, kind, fields in self._wal.replay(from_seq=wal_seq):
                if self._apply_wal_record(kind, fields):
                    identity = True
                replayed += 1
        if not identity:
            # Neither checkpoint nor boot record survived (corrupt blob AND
            # the boot record compacted away). This boots as a FRESH session
            # — roll back anything replay already inserted, or ghost actors
            # stuck 'restarting' (no readopt timer arms) would squat names
            # and poison list_actors forever.
            self.actors.clear()
            self.named_actors.clear()
            self.pgs.clear()
            self.objects.clear()
            self.jobs.clear()
            self.store_bytes_used = 0
            return False
        self.local_store = store.make_store(create_arena=False)  # re-attach
        # Actors whose creation never reached a worker (registered/queued at
        # crash time — worker_id empty) restart their creation task NOW
        # instead of waiting out the 40s re-adoption deadline (which would
        # also burn restart budget for a worker that never existed).
        requeued = 0
        for astate in self.actors.values():
            if (
                astate.state == "restarting"
                and not astate.worker_id
                and astate.spec is not None
            ):
                astate.state = "pending"
                self._pin_args(astate.spec)
                self._enqueue(PendingTask(spec=astate.spec, retries_left=0))
                requeued += 1
        if requeued:
            self._schedule()
        # Re-apply PG reservations against head capacity exactly once, over
        # the MERGED (checkpoint + replay) table — bundles were reserved
        # pre-crash; remote nodes re-register with fresh availability, so
        # only the head's books need the deduction.
        for pg in self.pgs.values():
            for b, nid in zip(pg["bundles"], pg.get("bundle_nodes") or []):
                if nid == HEAD_NODE:
                    self._acquire(self.head, b)
        self._event("controller_restored", actors=len(self.actors),
                    objects=len(self.objects), replayed=replayed)
        asyncio.get_running_loop().call_later(
            rt_config.get("readopt_deadline_s"),
            lambda: asyncio.ensure_future(self._readopt_deadline()),
        )
        return True

    # Kept under its historical name for callers/tests that restore
    # explicitly.
    _load_snapshot = _restore_state

    def _apply_wal_record(self, kind: str, fields: dict) -> bool:
        """Apply one replayed WAL record to the directories. IDEMPOTENT by
        construction — replaying the same log twice reaches a fixpoint (the
        replay-idempotency test's invariant): creations skip existing
        entries, deaths re-set terminal states, connection-scoped records
        (workers, leases) are no-ops because that state cannot outlive the
        peer's TCP connection. Returns True for identity-bearing records
        (controller_boot)."""
        if kind == "controller_boot":
            # Fallback identity when no checkpoint landed before the crash.
            if not store.SESSION_TAG:
                store.set_session_tag(fields["session_tag"])
                self.port = fields["port"]
                self.object_store_memory = fields["object_store_memory"]
            return True
        if kind == "actor_registered":
            h = fields["actor"]
            if h in self.actors:
                return False
            astate = ActorState(
                actor_hex=h,
                spec=spec_from_proto_bytes(fields["spec"]),
                name=fields.get("name", ""),
                namespace=fields.get("namespace", "default"),
                handle_bytes=fields.get("handle", b""),
                detached=bool(fields.get("detached")),
            )
            astate.state = "restarting"
            self.actors[h] = astate
            astate.shard = self.actors.shard_for(h)
            if astate.name:
                self.named_actors.setdefault(
                    (astate.namespace, astate.name), h
                )
            return False
        astate = self.actors.get(fields.get("actor", ""))
        if kind == "actor_infeasible":
            if astate is None:
                a = ActorState(
                    actor_hex=fields["actor"], spec=None, state="dead"
                )
                a.init_error = TaskError(
                    RuntimeError(fields.get("error", "infeasible")), "",
                    "actor creation",
                )
                self.actors[fields["actor"]] = a
                a.shard = self.actors.shard_for(fields["actor"])
            return False
        if kind == "actor_alive":
            if astate is not None and astate.state != "dead":
                astate.worker_id = fields.get("worker") or astate.worker_id
                # Stays "restarting": alive again only when its worker
                # actually reconnects (h_register_worker re-adoption).
            return False
        if kind == "actor_restarting":
            if astate is not None and astate.state != "dead":
                astate.restarts_used = max(
                    astate.restarts_used, int(fields.get("restarts_used", 0))
                )
                astate.state = "restarting"
            return False
        if kind in ("actor_death", "actor_killed"):
            if astate is not None:
                astate.state = "dead"
                if fields.get("no_restart", True):
                    astate.spec = None
                for key, ah in list(self.named_actors.items()):
                    if ah == fields["actor"]:
                        del self.named_actors[key]
            return False
        if kind == "pg_created":
            self.pgs.setdefault(fields["pg"], {
                "bundles": fields["bundles"],
                "strategy": fields["strategy"],
                "name": fields.get("name", ""),
                "ready": bool(fields.get("ready")),
                "bundle_nodes": fields.get("bundle_nodes") or [],
                "bundle_avail": [dict(b) for b in fields["bundles"]],
            })
            return False
        if kind == "pg_placed":
            pg = self.pgs.get(fields["pg"])
            if pg is not None and not pg["ready"]:
                pg["bundle_nodes"] = fields.get("bundle_nodes") or []
                pg["bundle_avail"] = [dict(b) for b in pg["bundles"]]
                pg["ready"] = True
            return False
        if kind == "pg_removed":
            self.pgs.pop(fields["pg"], None)
            return False
        if kind == "object_ready":
            obj = self._obj(fields["id"])
            if obj.status != "ready":  # checkpoint overlap / second replay
                obj.status = "ready"
                obj.inline = fields.get("inline")
                obj.size = int(fields.get("size", 0))
                obj.expected = True
                if fields.get("contains") and not obj.contains:
                    obj.contains = list(fields["contains"])
                    for ch in obj.contains:
                        self._obj(ch).pinned += 1
            if fields.get("name"):
                node = fields.get("node", HEAD_NODE)
                if node not in obj.locations:
                    obj.locations[node] = fields["name"]
                    if node == HEAD_NODE:
                        # Mirror the live accounting (_mark_ready): a head
                        # shm copy counts against the arena budget.
                        self.store_bytes_used += int(fields.get("size", 0))
            return False
        if kind == "object_freed":
            obj = self.objects.pop(fields["id"], None)
            if obj is not None:
                if HEAD_NODE in obj.locations:
                    self.store_bytes_used -= obj.size
                for ch in obj.contains:
                    inner = self.objects.get(ch)
                    if inner is not None:
                        inner.pinned = max(0, inner.pinned - 1)
            return False
        # worker_registered / lease_granted / lease_returned /
        # named-actor forensics: connection-scoped — the state cannot
        # outlive the peer's conn, which did not survive the crash. Workers
        # re-register live; lease holders re-request. Recorded for
        # forensics and the chaos suite's ordering assertions only.
        return False

    async def _readopt_deadline(self):
        """Actors still 'restarting' after the reconnect window lost their
        worker during the outage — run the normal death path so they restart
        from spec (or die) instead of queueing calls forever."""
        for actor_hex, astate in list(self.actors.items()):
            if astate.state != "restarting":
                continue
            ws = self.workers.get(astate.worker_id)
            if ws is not None and ws.state == ACTOR and ws.actor_hex == actor_hex:
                continue  # reconnected fine
            self._event("actor_readopt_timeout", actor=actor_hex)
            await self._on_actor_worker_death(actor_hex)

    def _write_session_info(self):
        """address.json + /tmp/ray_tpu/session_latest symlink — CLI discovery
        (reference analog: ray's session_latest convention)."""
        import json

        info = {
            "address": f"{self.node_ip}:{self.port}",
            "metrics_url": f"http://{self.node_ip}:{self.metrics_port}/metrics",
            "session_dir": self.session_dir,
            "pid": os.getpid(),
            # Local CLI/driver discovery; remote joiners get the token
            # out-of-band (documented in README multi-host bring-up).
            "auth_token": rpc_auth_token(),
        }
        if getattr(self, "dashboard", None) is not None:
            info["dashboard_url"] = f"http://{self.node_ip}:{self.dashboard.port}"
        # 0600: the file carries the auth token — other local users must not
        # read their way past the handshake on a multi-user machine.
        path = os.path.join(self.session_dir, "address.json")
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w") as f:
            json.dump(info, f)
        link = "/tmp/ray_tpu/session_latest"
        try:
            os.makedirs("/tmp/ray_tpu", exist_ok=True)
            tmp = f"{link}.{os.getpid()}"
            os.symlink(self.session_dir, tmp)
            os.replace(tmp, link)
        except OSError:
            pass

    async def serve_forever(self):
        await self._shutdown_event.wait()
        await self._teardown()

    async def _teardown(self):
        for j in self.jobs.values():  # supervised jobs die with the session
            proc = j.get("proc")
            try:
                if proc is not None and proc.poll() is None:
                    proc.terminate()
                elif proc is None and j.get("pid"):
                    os.kill(j["pid"], 15)
            except OSError:
                pass
        for node in self.nodes.values():
            if node.conn is not None and node.alive:
                try:
                    await node.conn.send({"type": "exit"})
                except Exception:  # noqa: BLE001
                    pass
        for ws in self.workers.values():
            if ws.conn is not None:
                try:
                    await ws.conn.send({"type": "exit"})
                except Exception:  # noqa: BLE001
                    pass
        await asyncio.sleep(0.05)
        # list(): the fork-flusher thread may still be registering
        # PidHandles mid-burst; a live dict would raise mid-iteration.
        for proc in list(self._worker_procs.values()):
            if proc.poll() is None:
                proc.terminate()
        for obj in self.objects.values():
            if obj.shm_name:
                self.local_store.release(obj.shm_name, unlink=True)
        self.local_store.close_all(unlink=False)
        arena = getattr(self.local_store, "arena", None)
        if arena is not None:
            arena.unlink()  # whole-session segment; workers are exiting
        if self.standalone:  # graceful end — session no longer restorable
            store.mark_restorable(store.SESSION_TAG, False)
        if self._server:
            self._server.close()
        if self._wal is not None:
            self._wal.close()
        if getattr(self, "_bulk_server", None) is not None:
            self._bulk_server.stop()
        if getattr(self, "_forkserver", None) is not None:
            self._forkserver.stop()
        for sh in self.shards:
            sh.stop()

    # ------------------------------------------------------------- workers
    def _spawn_worker(
        self,
        tpu: bool = False,
        node: Optional[NodeState] = None,
        live_count: Optional[int] = None,
        force: bool = False,
        isolation: Optional[dict] = None,
    ):
        """Spawn a worker on `node` (default head). Remote nodes spawn via
        their agent (reference: raylet `WorkerPool::StartWorkerProcess`).
        `live_count` (alive workers on the node) skips the O(workers) scan
        when the caller already counted (the scheduler's per-pass cache).
        `force` bypasses the task-pool cap — ACTORS own dedicated processes
        (reference semantics: tens of thousands of actor workers), so the
        cap that bounds task-worker prestarting must not deadlock actor
        creation."""
        node = node or self.head
        # Boot-rate limit (ALL spawn kinds, incl. forced actor spawns): each
        # booting interpreter costs ~2s of CPU; an unbounded burst (observed:
        # 500+ booting during a 2000-actor envelope probe) thrashes the
        # machine until registrations time out. Deferral is safe — every
        # registration fires _schedule, which re-flushes pending spawn
        # demand until it drains.
        # The spawn ledger IS the in-flight boot set (one entry per spawn,
        # removed at registration/expiry) — counting it is O(1)-ish where
        # the old per-call worker-table scan was O(workers) and went
        # quadratic across a 2,000-spawn wave.
        booting = len(self._spawn_ledger)
        boot_cap = rt_config.get("worker_boot_concurrency")
        if self._forkserver is not None and self._forkserver.usable:
            # Forked workers skip the ~2s interpreter boot the cap was sized
            # for; registration (the remaining cost) tolerates a deeper queue.
            boot_cap *= 4
        if booting >= boot_cap:
            return
        if tpu:
            if node.spawning_tpu > 0:
                return
            node.spawning_tpu += 1
        else:
            if live_count is None:
                # Task-POOL occupancy only: dedicated ACTOR workers are
                # excluded, else long-lived actors eat the cap and starve
                # plain tasks of workers forever.
                live_count = sum(
                    1 for w in self.workers.values()
                    if w.state not in (DEAD, ACTOR) and w.node_id == node.node_id
                    and not w.env_key  # isolated workers are outside the pool
                )
            if not force and node.spawning + live_count >= self._max_workers:
                return
        node.spawning += 1
        self._spawn_ledger.append((node.node_id, time.monotonic(), tpu))
        worker_id = f"w{next(self._worker_counter)}"
        self._event("worker_spawn", worker=worker_id, forced=force)
        if isolation is not None:
            # Registration looks the env_key up by worker_id (the worker
            # itself doesn't need to know its isolation hash).
            self._worker_env_keys[worker_id] = isolation["key"]
            self._iso_booting[(node.node_id, isolation["key"])] = (
                time.monotonic(), worker_id,
            )
        if node.conn is not None:
            try:
                node.conn.post({
                    "type": "spawn_worker", "worker_id": worker_id,
                    "tpu": tpu, "isolation": isolation,
                })
            except ConnectionError:
                pass  # node dying — ledger expiry reclaims the boot budget
            return
        # Spawn-env template, built once: dict(os.environ) iterates the
        # environ Mapping in Python (a decode per key per spawn — measured
        # ~2.5s per 1,000-spawn wave); a plain dict copy is C-speed.
        base = getattr(self, "_spawn_env_base", None)
        if base is None:
            base = dict(os.environ)
            pkg_root0 = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
            base["PYTHONPATH"] = pkg_root0 + os.pathsep + base.get("PYTHONPATH", "")
            base["RAY_TPU_ADDRESS"] = f"{self.node_ip}:{self.port}"
            base["RAY_TPU_NODE_IP"] = self.node_ip  # workers bind/advertise here
            base["RAY_TPU_SESSION_DIR"] = self.session_dir
            base["RAY_TPU_SESSION_TAG"] = store.SESSION_TAG
            base["PYTHONUNBUFFERED"] = "1"  # log tailing needs unbuffered stdout
            self._spawn_env_base = base
        env = dict(base)
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
        env["RAY_TPU_WORKER_ID"] = worker_id
        if tpu:
            env["RAY_TPU_WORKER_TPU"] = "1"
        else:
            # CPU worker: strip the TPU plugin hookup. This both (a) isolates
            # the chip — only workers granted a TPU resource may attach it
            # (reference precedent: TPU_VISIBLE_CHIPS, `accelerators/tpu.py:30`)
            # — and (b) keeps worker startup fast (the site-level TPU plugin
            # registration imports jax, ~2s of CPU per process).
            env["RAY_TPU_WORKER_TPU"] = "0"
            env.pop("PALLAS_AXON_POOL_IPS", None)
            if env.get("JAX_PLATFORMS", "").lower() in ("", "axon", "tpu"):
                env["JAX_PLATFORMS"] = "cpu"
        log_path = os.path.join(self.session_dir, f"worker-{worker_id}.log")
        argv = [sys.executable, "-m", "ray_tpu.core.worker_main"]
        if isolation is not None:
            # conda/container wrap — never forkserver-able (the whole point
            # is a different interpreter/filesystem).
            from ..runtime_env.isolation import build_argv

            env["RAY_TPU_ENV_KEY"] = isolation["key"]
            try:
                argv = build_argv(isolation, argv, env, self.session_dir)
            except Exception as e:  # noqa: BLE001 — binary missing on node
                self._iso_spawn_failed(node, worker_id, isolation, repr(e), tpu=tpu)
                self._schedule()
                return
        if (
            not tpu and isolation is None
            and self._forkserver is not None and self._forkserver.usable
        ):
            # Warm path: ~10 ms fork from the pre-imported template. Fork
            # preserves the no-pdeathsig property (the template, not the
            # controller, is the parent — and it ignores SIGCHLD). Async +
            # batched: the round trip must not block the event loop, and a
            # creation burst coalesces into few template trips. Failed
            # trips recover via spawn-ledger expiry (see spawn_async).
            self._forkserver.spawn_async(
                worker_id, env, log_path, self._worker_procs.__setitem__
            )
            return
        self._worker_procs[worker_id] = self._popen_cold(
            argv, env, log_path, pkg_root
        )

    @staticmethod
    def _popen_cold(argv, env, log_path, cwd) -> subprocess.Popen:
        log_f = open(log_path, "ab")
        return subprocess.Popen(
            argv,
            env=env,
            stdout=log_f,
            stderr=subprocess.STDOUT,
            cwd=cwd,
            # NO pdeathsig here: head workers deliberately survive a
            # controller crash so a restarted controller re-adopts them
            # (controller FT). Orphan cleanup is the worker's reconnect
            # grace timeout, not process lineage.
        )

    def _spawn_isolated(self, node: "NodeState", spec, tpu: bool = False):
        """Spawn a worker wrapped in the task's conda/container isolation
        (reference: raylet starting runtime-env workers through the agent's
        env setup, `worker_pool.cc` PopWorker w/ runtime_env_hash). One boot
        per (node, key) at a time, with a grace window so a dead spawn
        doesn't wedge the key forever."""
        from ..runtime_env.isolation import resolve

        isolation = resolve(spec.options.runtime_env)
        if isolation is None:
            return
        key = isolation["key"]
        if (node.node_id, key) in self._iso_unavailable:
            # The binary is missing on THIS node; another may serve the env.
            alt = self._iso_candidate(spec, key)
            if alt is None:
                self._fail_iso_tasks_without_candidates(key)
                return
            node = alt
        booting = self._iso_booting.get((node.node_id, key))
        if booting is not None:
            last, prev_worker = booting
            attempts_so_far = self._iso_attempts.get((node.node_id, key), 0)
            # Grace grows with attempts: slow env setups (image pull, heavy
            # conda activate) on REMOTE nodes are unobservable from here —
            # the widening window keeps them from being misread as dead.
            grace = rt_config.get("iso_boot_grace_s") * (attempts_so_far + 1)
            if time.monotonic() - last < grace:
                return  # a worker for this env is already booting there
            proc = self._worker_procs.get(prev_worker)
            alive = (
                proc is not None and hasattr(proc, "poll") and proc.poll() is None
            ) or (
                # Agent-spawned: no proc handle here, but the agent reports
                # spawn liveness in health-probe replies — a slow remote env
                # setup (5-min image pull) must extend the window like local
                # slow boots do, not burn the attempt budget (ADVICE r4).
                proc is None and prev_worker in node.agent_alive_workers
            )
            if alive:
                # Still ALIVE past the grace — a slow boot, not a dead one.
                # Extend the window rather than double-spawning or counting
                # a failure.
                self._iso_booting[(node.node_id, key)] = (
                    time.monotonic(), prev_worker,
                )
                return
            # Dead (or agent-spawned and unobservable) without registering:
            # bad conda env name, unpullable image, ... Count it exactly
            # once — the entry is POPPED here and only re-armed by
            # _spawn_worker when a new spawn actually launches, so a
            # boot-cap deferral can never inflate the counter. After a few
            # dead attempts the node stops being a candidate, which
            # surfaces RuntimeEnvSetupError to the queued tasks — the
            # reference's RUNTIME_ENV_SETUP_FAILED contract
            # (`python/ray/_private/runtime_env/container.py`).
            # NOTE: _worker_env_keys[prev_worker] is kept for unobservable
            # spawns — if the spawn is merely slow (remote) and registers
            # later, its env key must still resolve or an ISOLATED worker
            # would join the plain pool and run non-isolated tasks in the
            # wrong world. Registration pops it; a truly dead attempt leaks
            # one short string, bounded at 3 per (node, env).
            self._iso_booting.pop((node.node_id, key), None)
            if proc is not None:
                self._worker_procs.pop(prev_worker, None)
                self._worker_env_keys.pop(prev_worker, None)
            attempts = attempts_so_far + 1
            self._iso_attempts[(node.node_id, key)] = attempts
            if attempts >= 3:
                self._iso_unavailable[(node.node_id, key)] = (
                    f"isolated worker died before registering "
                    f"{attempts} times (broken env?)"
                )
                self._fail_iso_tasks_without_candidates(key)
                return
        self._spawn_worker(tpu=tpu, node=node, force=True, isolation=isolation)

    def _iso_candidate(self, spec, key: str) -> Optional["NodeState"]:
        """An alive node not yet marked binary-less for this env whose
        TOTAL resources could host the task."""
        for node in self.nodes.values():
            if (
                node.alive
                and (node.node_id, key) not in self._iso_unavailable
                and all(
                    node.total.get(k, 0) >= v
                    for k, v in spec.resources.items()
                )
            ):
                return node
        return None

    def _fail_iso_tasks_without_candidates(self, key: str):
        """Fail queued tasks for this env ONLY once no alive node can host
        it (reference: RUNTIME_ENV_SETUP_FAILED) — a missing binary is a
        per-node property, not a cluster verdict."""
        from ..runtime_env import RuntimeEnvSetupError

        doomed = [
            pt for pt in self.ready_queue
            if _task_env_key(pt.spec) == key
            and self._iso_candidate(pt.spec, key) is None
        ]
        if not doomed:
            return
        why = "; ".join(sorted({
            v for (n, k), v in self._iso_unavailable.items() if k == key
        }))
        for pt in doomed:
            self.ready_queue.remove(pt)
            self._fail_task(
                pt,
                TaskError(
                    RuntimeEnvSetupError(
                        f"no node can host this environment: {why}"
                    ),
                    "", pt.spec.name,
                ),
            )

    def _iso_spawn_failed(self, node, worker_id: str, isolation: dict,
                          why: str, tpu: bool = False):
        """Isolated spawn couldn't even exec (missing conda/podman on this
        node): give back the FULL spawn bookkeeping (counter + ledger, like
        registration does), mark the node unavailable for the env, and fail
        only tasks no other node can serve."""
        node.spawning = max(0, node.spawning - 1)
        if tpu:
            node.spawning_tpu = max(0, node.spawning_tpu - 1)
        for i, entry in enumerate(self._spawn_ledger):
            if entry[0] == node.node_id and entry[2] == tpu:
                del self._spawn_ledger[i]
                break
        self._worker_env_keys.pop(worker_id, None)
        key = isolation["key"]
        self._iso_booting.pop((node.node_id, key), None)
        self._iso_unavailable[(node.node_id, key)] = why
        self._fail_iso_tasks_without_candidates(key)

    # ---------------------------------------------------------- connection
    async def _on_connection(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter):
        conn = Connection(reader, writer, expected_token=rpc_auth_token())
        meta = {"kind": None, "worker_id": None, "conn_id": next(self._conn_counter)}
        self._conns_by_id[meta["conn_id"]] = conn

        async def on_push(msg: dict):
            try:
                await self._dispatch_msg(conn, meta, msg)
            except Exception:  # noqa: BLE001
                traceback.print_exc()

        async def on_close():
            await self._on_disconnect(conn, meta)

        conn.on_push = on_push
        conn.on_close = on_close
        conn.start()

    # Handlers that may await object readiness. They only READ shared state, so
    # they run as detached tasks — otherwise a long-poll would block the
    # connection's read loop and deadlock clients that get() on one thread
    # while another thread produces the object.
    _LONG_POLL = frozenset({"get_object", "get_objects", "wait_objects",
                            "tail_logs", "stream_next", "request_lease"})

    async def _dispatch_msg(self, conn: Connection, meta: dict, msg: dict):
        mtype = msg["type"]
        handler = getattr(self, f"h_{mtype}", None)
        if handler is None:
            if msg.get("req_id") is not None:
                await conn.respond(msg["req_id"], {"error": f"unknown message {mtype}"})
            return

        async def run():
            result = await handler(conn, meta, msg)
            if msg.get("req_id") is not None:
                conn.respond_nowait(msg["req_id"], result)

        if mtype in self._LONG_POLL:
            asyncio.ensure_future(run())
        else:
            await run()

    async def _on_disconnect(self, conn: Connection, meta: dict):
        # A dead process's refs die with it (reference: borrower death
        # detection via pubsub channel close).
        conn_id = meta.get("conn_id")
        if conn_id is not None:
            self._conns_by_id.pop(conn_id, None)
        # Leases die with their holder.
        for worker_id in meta.get("leases") or ():
            ws = self.workers.get(worker_id)
            if ws is not None and ws.leased_to == conn_id:
                self._release_lease(ws, requeue=False)
        if meta.get("leases"):
            self._schedule()
        if conn_id is not None:
            for hex_id in self._conn_refs.pop(conn_id, ()):
                obj = self.objects.get(hex_id)
                if obj is not None:
                    obj.holders.discard(conn_id)
                    self._maybe_gc(hex_id)
        if meta["kind"] == "worker":
            # Only the CURRENT registration's conn declares the worker dead:
            # a reconnecting worker can race itself during a head failover
            # (two register frames, second replaces the first), and the
            # stale conn's close must not kill the live re-registration —
            # observed killing a just-re-adopted actor host, which then
            # burned the actor's restart budget for a worker still alive.
            cur_ws = self.workers.get(meta["worker_id"])
            if cur_ws is None or cur_ws.conn is conn:
                await self._on_worker_death(meta["worker_id"])
        elif meta["kind"] == "node":
            # Only the CURRENT registration's conn declares the node dead: a
            # re-announced agent (failover reconnect) may have replaced this
            # conn already — its stale close must not kill the fresh record.
            cur = self.nodes.get(meta["node_id"])
            if cur is None or cur.conn is conn:
                await self._on_node_death(meta["node_id"])
        elif meta["kind"] == "driver":
            self.drivers.discard(conn)
            if not self.drivers and not self.standalone:
                # Last driver gone → end the session.
                self._shutdown_event.set()

    # ----------------------------------------------------------- handlers
    async def h_register_driver(self, conn, meta, msg):
        meta["kind"] = "driver"
        # A worker's nested-API backend registers as a driver too — adopt
        # its node so gets materialize objects in ITS node's arena (pulling
        # into the head instead was a triple copy on one machine and a
        # correctness hole across machines: the worker would try to open a
        # /dev/shm name that only exists on the head).
        meta["node_id"] = msg.get("node_id", HEAD_NODE)
        self.drivers.add(conn)
        return {
            "ok": True,
            "session_dir": self.session_dir,
            "session_tag": store.SESSION_TAG,
            # Controller wall clock for the registrant's RTT-midpoint
            # flight-recorder clock alignment (see cluster_backend._connect).
            "time": time.time(),
        }

    async def h_register_client(self, conn, meta, msg):
        # Secondary connection from a worker's nested-API backend (or an
        # agent's fetch client). Carries its node so gets resolve locally.
        meta["kind"] = "client"
        meta["node_id"] = msg.get("node_id", HEAD_NODE)
        return {"ok": True, "time": time.time()}

    async def h_register_worker(self, conn, meta, msg):
        worker_id = msg["worker_id"]
        node_id = msg.get("node_id", HEAD_NODE)
        meta["kind"] = "worker"
        meta["worker_id"] = worker_id
        # Duplicate registration (a reconnecting worker racing itself across
        # a head failover — two register frames, second replaces the first):
        # release the replaced record's capacity grant BEFORE the re-adoption
        # block below re-acquires, or the node's books double-deduct the
        # actor's resources on every extra frame.
        old_ws = self.workers.get(worker_id)
        if old_ws is not None and old_ws.assigned:
            if old_ws.blocked:
                old_ws.assigned = {}
                old_ws.assigned_pg = None
            else:
                self._grant_release(old_ws)
        # Prefer the worker's self-report (survives controller restarts —
        # the in-memory map doesn't); fall back to the spawn-time record.
        env_key = msg.get("env_key") or self._worker_env_keys.pop(worker_id, "")
        self._worker_env_keys.pop(worker_id, None)
        if env_key:
            self._iso_booting.pop((node_id, env_key), None)
            self._iso_attempts.pop((node_id, env_key), None)
            # A registered worker PROVES the env works here — undo any
            # unavailable verdict a slow earlier boot may have left.
            self._iso_unavailable.pop((node_id, env_key), None)
        ws = WorkerState(
            worker_id=worker_id,
            conn=conn,
            pid=msg.get("pid", 0),
            state=IDLE,
            has_tpu=bool(msg.get("has_tpu")),
            node_id=node_id,
            direct_addr=msg.get("direct_addr", ""),
            env_key=env_key,
        )
        self.workers[worker_id] = ws
        # Re-adoption after a controller restart: a surviving actor worker
        # reconnects carrying its actor id — restore the binding and wake
        # the actor's queued calls (reference analog: GCS restart replaying
        # actor tables + workers re-registering).
        actor_hex = msg.get("actor_hex")
        if actor_hex:
            astate = self.actors.get(actor_hex)
            if astate is not None and astate.state != "dead":
                ws.state = ACTOR
                ws.actor_hex = actor_hex
                astate.worker_id = worker_id
                # Re-acquire the actor's capacity grant or the books show
                # its resources free (double-booking). PG-backed actors skip
                # the deduction: the snapshotted bundle_avail already
                # reflects their consumption.
                if astate.spec is not None:
                    demand = astate.spec.resources
                    strat = astate.spec.options.scheduling_strategy
                    if (
                        isinstance(strat, PlacementGroupSchedulingStrategy)
                        and strat.placement_group is not None
                    ):
                        pg_hex = strat.placement_group.id.hex()
                        bidx = max(strat.placement_group_bundle_index, 0)
                        ws.assigned = dict(demand)
                        ws.assigned_pg = (pg_hex, bidx)
                    else:
                        node0 = self.nodes.get(node_id)
                        if node0 is not None:
                            self._acquire(node0, demand)
                        ws.assigned = dict(demand)
                self._set_actor_state(astate, "alive")
                self._event("actor_readopted", actor=actor_hex, worker=worker_id)
        node = self.nodes.get(node_id)
        if node is not None:
            node.spawning = max(0, node.spawning - 1)
            if ws.has_tpu:
                node.spawning_tpu = max(0, node.spawning_tpu - 1)
            for i, entry in enumerate(self._spawn_ledger):
                if entry[0] == node_id and entry[2] == ws.has_tpu:
                    del self._spawn_ledger[i]
                    break
        self._worker_arrival.set()
        self._worker_arrival.clear()
        # Connection-scoped (a worker entry cannot outlive its conn, so
        # replay is a no-op) — recorded for forensics/ordering only.
        self._wal_append("worker_registered", worker=worker_id,
                         node=node_id, actor=actor_hex or "")
        self._event("worker_registered", worker=worker_id)
        self._schedule()
        return {"ok": True, "time": time.time()}

    async def h_register_node(self, conn, meta, msg):
        """A node agent joined (reference: `GcsNodeManager::HandleRegisterNode`).
        The docstring seam promised in round 1 (`register_node`) — now real."""
        node_id = msg["node_id"]
        existing = self.nodes.get(node_id)
        if (
            existing is not None
            and existing.alive
            and existing.conn is not None
            and not existing.conn._closed
        ):
            return {"ok": False, "error": f"node id {node_id} already registered"}
        if existing is not None:
            # Re-announce after a head failover (or an agent-side reconnect
            # whose old conn the head hasn't reaped yet): replace the stale
            # record — the fresh registration carries current capacity, and
            # the node's workers re-register themselves.
            self.nodes.pop(node_id, None)
        meta["kind"] = "node"
        meta["node_id"] = node_id
        total = {k: float(v) for k, v in (msg.get("resources") or {}).items()}
        self.nodes[node_id] = NodeState(
            node_id=node_id,
            conn=conn,
            fetch_addr=msg.get("fetch_addr", ""),
            bulk_addr=msg.get("bulk_addr", ""),
            dispatch=bool(msg.get("local_dispatch")),
            total=dict(total),
            available=dict(total),
            session_tag=msg.get("session_tag", ""),
            object_store_memory=msg.get("object_store_memory", 0),
            labels={k: str(v) for k, v in (msg.get("labels") or {}).items()},
        )
        self._event("node_added", node=node_id, resources=total)
        self._schedule()  # also retries pending PGs against the new capacity
        return {"ok": True, "time": time.time()}

    def _retry_pending_pgs(self):
        """Re-attempt placement of PGs that are not ready — new capacity (an
        autoscaled/added node, or resources freed by finished tasks) may
        satisfy them (reference:
        `GcsPlacementGroupManager::SchedulePendingPlacementGroups`).

        Partially-placed PGs (a node died, re-placement was infeasible) keep
        their surviving bundles' reservations: only the `None` slots are
        re-placed, seeded with the surviving nodes so STRICT_SPREAD keeps its
        distinctness invariant."""
        for pg_hex, pg in self.pgs.items():
            if pg["ready"]:
                continue
            if pg["bundle_nodes"] and any(n is not None for n in pg["bundle_nodes"]):
                missing = [i for i, n in enumerate(pg["bundle_nodes"]) if n is None]
                surviving = {n for n in pg["bundle_nodes"] if n is not None}
                placement = self._place_bundles(
                    [pg["bundles"][i] for i in missing],
                    pg["strategy"],
                    occupied=surviving,
                )
                if placement is None:
                    continue
                for i, nid in zip(missing, placement):
                    self._acquire(self.nodes[nid], pg["bundles"][i])
                    pg["bundle_nodes"][i] = nid
                    pg["bundle_avail"][i] = dict(pg["bundles"][i])
            else:
                placement = self._place_bundles(pg["bundles"], pg["strategy"])
                if placement is None:
                    continue
                for b, nid in zip(pg["bundles"], placement):
                    self._acquire(self.nodes[nid], b)
                pg["bundle_nodes"] = placement
                pg["bundle_avail"] = [dict(b) for b in pg["bundles"]]
            pg["ready"] = True
            self._wal_append("pg_placed", pg=pg_hex,
                             bundle_nodes=pg["bundle_nodes"])
            self._event("pg_placed", pg=pg_hex)

    async def h_shutdown(self, conn, meta, msg):
        self._shutdown_event.set()
        return {"ok": True}

    # ------------------------------------------------------------- objects
    def _obj(self, hex_id: str) -> ObjectState:
        obj = self.objects.get(hex_id)
        if obj is None:
            obj = self.objects[hex_id] = ObjectState()
        return obj

    def _mark_ready(
        self,
        hex_id: str,
        inline: Optional[bytes] = None,
        shm_name: Optional[str] = None,
        size: int = 0,
        node_id: str = HEAD_NODE,
        contains: Optional[List[str]] = None,
    ):
        obj = self._obj(hex_id)
        obj.status = "ready"
        obj.inline = inline
        if contains and not obj.contains:  # first registration only (a
            # reconstruction re-run re-reports the same nested ids)
            obj.contains = list(contains)
            for h in obj.contains:
                self._obj(h).pinned += 1
        if shm_name:
            obj.locations[node_id] = shm_name
        obj.size = size
        # WAL: the directory entry must survive a head crash in the window
        # before the next checkpoint — shm payloads outlive the head in the
        # arena (kill -9 skips teardown) and inline payloads ride the record
        # itself, so a put acknowledged to the client stays gettable across
        # failover. Freed ids are tombstoned below (_free_object).
        self._wal_append(
            "object_ready", id=hex_id, inline=inline, name=shm_name or "",
            size=size, node=node_id, contains=list(contains or ()),
        )
        obj.last_access = time.monotonic()
        if shm_name and node_id == HEAD_NODE:
            self.store_bytes_used += size
        for ev in obj.events:
            ev.set()
        obj.events.clear()
        # Unblock tasks waiting on this object.
        for task_hex in list(obj.dependents):
            pt = self.waiting_tasks.get(task_hex)
            if pt is not None:
                pt.deps_remaining.discard(hex_id)
                if not pt.deps_remaining:
                    del self.waiting_tasks[task_hex]
                    self.ready_queue.append(pt)
        obj.dependents.clear()
        self._maybe_spill()
        self._maybe_gc(hex_id)  # refs may have been dropped while pending
        self._schedule()

    def _store_error_object(self, hex_id: str, err: TaskError):
        frame = serialization.pack(err)
        self._mark_ready(hex_id, inline=frame)

    def _location_payload(self, obj: ObjectState, node_id: str = HEAD_NODE) -> dict:
        obj.last_access = time.monotonic()
        if obj.inline is not None:
            return {"status": "inline", "data": obj.inline}
        name = obj.locations.get(node_id)
        if name is not None:
            return {"status": "shm", "name": name, "size": obj.size}
        if obj.spilled_path is not None and obj.spilled_node == node_id:
            return {"status": "spilled", "path": obj.spilled_path}
        if obj.locations or obj.spilled_path:
            return {"status": "remote"}  # caller must _ensure_local first
        return {"status": "lost"}

    # ------------------------------------------------- cross-node transfer
    def _source_for(self, obj: ObjectState) -> Optional[dict]:
        """Pick the LEAST-LOADED live copy (each completed pull mints a new
        copy, so concurrent fan-out self-organizes into a broadcast tree —
        reference analog: `PushManager` chunked push + location-aware pulls);
        falls back to the spill file."""
        best = None
        best_load = None
        for nid, name in obj.locations.items():
            node = self.nodes.get(nid)
            if node is None or not node.alive:
                continue
            load = self._src_active.get(nid, 0)
            if best is None or load < best_load:
                addr = (
                    f"{self.node_ip}:{self.port}" if nid == HEAD_NODE
                    else node.fetch_addr
                )
                bulk = self._bulk_addr if nid == HEAD_NODE else node.bulk_addr
                best = {"addr": addr, "name": name, "node": nid, "bulk": bulk}
                best_load = load
        if best is not None:
            return best
        if obj.spilled_path is not None:
            nid = obj.spilled_node
            node = self.nodes.get(nid)
            if node is not None and (nid == HEAD_NODE or node.alive):
                addr = f"{self.node_ip}:{self.port}" if nid == HEAD_NODE else node.fetch_addr
                bulk = self._bulk_addr if nid == HEAD_NODE else node.bulk_addr
                return {"addr": addr, "path": obj.spilled_path, "node": nid,
                        "bulk": bulk}
        return None

    async def _ensure_local(self, node_id: str, hex_id: str):
        """Materialize a ready object on `node_id` (controller-directed pull —
        reference analog: `PullManager` asking the owner's `PushManager`)."""
        obj = self._obj(hex_id)
        if obj.inline is not None or node_id in obj.locations:
            return
        if (obj.size or 0) >= (1 << 30) and rt_config.get("transfer_log_big"):
            # Stderr diagnostic (session log): big-object transfer routing.
            print(
                f"ensure_local node={node_id} id={hex_id[:8]} "
                f"size={(obj.size or 0) >> 20}MiB",
                flush=True, file=__import__("sys").stderr,
            )
        if obj.spilled_path is not None and obj.spilled_node == node_id:
            return
        key = (node_id, hex_id)
        fut = self._pulls.get(key)
        if fut is not None:
            await fut
            return
        fut = asyncio.get_running_loop().create_future()
        self._pulls[key] = fut
        src = None
        try:
            # Broadcast shaping: wait while every source is already serving
            # its quota of pulls — each completed pull adds a copy, so
            # waiters fan out over fresh sources (binomial-tree growth)
            # instead of hammering the origin N-wide.
            per_src = rt_config.get("transfer_pulls_per_source")
            while True:
                src = self._source_for(obj)
                if src is None:
                    raise RuntimeError(f"object {hex_id[:12]} has no live copy")
                if self._src_active.get(src["node"], 0) < per_src:
                    break
                waiter = asyncio.get_running_loop().create_future()
                self._transfer_waiters.append(waiter)
                await waiter
                if node_id in obj.locations:  # a racer materialized it here
                    fut.set_result(None)
                    return
            self._src_active[src["node"]] = self._src_active.get(src["node"], 0) + 1
            try:
                # Deadline scales with size AND with possible queueing behind
                # the destination's pull-admission quota (the per-chunk
                # progress deadline lives agent-side; this is a backstop).
                timeout = rt_config.get("pull_timeout_s") + (
                    obj.size * (1 + rt_config.get("transfer_max_pulls"))
                    / (16 * 1024 * 1024) if obj.size else 0.0
                )
                if node_id == HEAD_NODE:
                    name, size = await self._fetch_into_head(
                        dict(src, id=hex_id), obj.size
                    )
                    self.store_bytes_used += size
                    self._maybe_spill()  # pulls count against the memory cap
                else:
                    node = self.nodes[node_id]
                    req = {"type": "pull_object", "id": hex_id,
                           "addr": src["addr"], "size": obj.size or 0,
                           "bulk": src.get("bulk", "")}
                    if "name" in src:
                        req["name"] = src["name"]
                    else:
                        req["path"] = src["path"]
                    resp = await node.conn.request(req, timeout=timeout)
                    if not resp.get("ok"):
                        raise RuntimeError(f"pull failed: {resp.get('error')}")
                    name = resp["name"]
            finally:
                self._src_active[src["node"]] -= 1
                waiters, self._transfer_waiters = self._transfer_waiters, []
                for w in waiters:
                    if not w.done():
                        w.set_result(None)
            obj.locations[node_id] = name
            self._event("object_transferred", object=hex_id, to=node_id, src=src["node"])
            fut.set_result(None)
        except BaseException as e:  # noqa: BLE001
            fut.set_exception(e)
            # Consume the exception if nobody else awaits this future.
            fut.exception()
            raise
        finally:
            self._pulls.pop(key, None)

    async def _fetch_into_head(self, src: dict, size_hint: int = 0):
        """Materialize a remote object in the HEAD store — the same chunked
        pull client the agents use (streams into shm; no heap staging).
        Returns (name, size)."""
        from .node_agent import pull_chunked

        hex_id = src.get("id", "")
        if src["node"] == HEAD_NODE:
            if "name" in src:
                return src["name"], self.local_store.raw_size(src["name"])
            with open(src["path"], "rb") as f:
                data = f.read()
            return self.local_store.create_raw(hex_id, data)
        conn = self._fetch_conns.get(src["node"])
        if conn is None or conn._closed:
            host, port = src["addr"].rsplit(":", 1)
            reader, writer = await open_rpc_connection(host, int(port))
            conn = Connection(reader, writer)
            conn.start()
            self._fetch_conns[src["node"]] = conn
        where = {"name": src["name"]} if "name" in src else {"path": src["path"]}
        if src.get("bulk"):
            where["bulk"] = src["bulk"]
        return await pull_chunked(
            conn, where, self.local_store, hex_id, size_hint=size_hint
        )

    async def h_stat_object(self, conn, meta, msg):
        from .node_agent import serve_fetch

        try:
            return serve_fetch(self.local_store, dict(msg, type="stat_object"))
        except Exception as e:  # noqa: BLE001
            return {"error": repr(e)}

    async def h_fetch_chunk(self, conn, meta, msg):
        from .node_agent import serve_fetch

        try:
            return serve_fetch(self.local_store, dict(msg, type="fetch_chunk"))
        except Exception as e:  # noqa: BLE001
            return {"error": repr(e)}

    async def h_fetch_object(self, conn, meta, msg):
        """Serve head-node object bytes to a pulling agent."""
        try:
            if msg.get("name"):
                data = self.local_store.read_raw(msg["name"])
            else:
                with open(msg["path"], "rb") as f:
                    data = f.read()
            return {"data": data}
        except Exception as e:  # noqa: BLE001
            return {"error": repr(e)}

    async def h_put_inline(self, conn, meta, msg):
        self._mark_ready(
            msg["id"], inline=msg["data"], size=len(msg["data"]),
            contains=msg.get("contains"),
        )
        return {"ok": True}

    async def h_put_data(self, conn, meta, msg):
        """Client-mode put of a large frame: store in the HEAD arena so it is
        accounted (store_bytes_used) and spillable like any worker object."""
        name, size = self.local_store.create_raw(msg["id"], msg["data"])
        self._mark_ready(
            msg["id"], shm_name=name, size=size, contains=msg.get("contains")
        )
        return {"ok": True}

    async def h_register_object(self, conn, meta, msg):
        self._mark_ready(
            msg["id"], shm_name=msg["name"], size=msg["size"],
            node_id=meta.get("node_id") or HEAD_NODE,
            contains=msg.get("contains"),
        )
        return {"ok": True}

    async def _wait_ready(self, obj: ObjectState, deadline: Optional[float]) -> bool:
        """Wait for an object's next readiness event (shared deadline across
        attempts). _mark_ready clears the event list; on timeout we remove
        ourselves so never-produced objects don't accumulate dead events."""
        ev = asyncio.Event()
        obj.events.append(ev)
        try:
            if deadline is None:
                await ev.wait()
            else:
                await asyncio.wait_for(ev.wait(), max(0.0, deadline - time.monotonic()))
            return True
        except asyncio.TimeoutError:
            return False
        finally:
            if ev in obj.events:
                obj.events.remove(ev)

    async def h_get_object(self, conn, meta, msg):
        return await self._get_object_payload(
            msg["id"], msg.get("timeout"), meta.get("node_id") or HEAD_NODE
        )

    async def h_get_objects(self, conn, meta, msg):
        """Batched resolve: one RPC for N refs (the reference's
        `CoreWorker::Get` takes the whole id list for the same reason —
        per-object round trips dominate many-ref gets)."""
        node_id = meta.get("node_id") or HEAD_NODE
        timeout = msg.get("timeout")
        payloads = await asyncio.gather(
            *(self._get_object_payload(h, timeout, node_id) for h in msg["ids"])
        )
        return {"locations": payloads}

    async def _get_object_payload(self, hex_id: str, timeout, node_id: str):
        deadline = None if timeout is None else time.monotonic() + timeout
        obj = self._obj(hex_id)
        if obj.status != "ready" and not await self._wait_ready(obj, deadline):
            return {"status": "timeout"}
        for _ in range(4):  # transfer, with lineage re-execution on loss
            payload = self._location_payload(obj, node_id)
            if payload["status"] == "remote":
                try:
                    await self._ensure_local(node_id, hex_id)
                except Exception:  # noqa: BLE001
                    continue  # copies vanished mid-pull; re-evaluate
                payload = self._location_payload(obj, node_id)
            if payload["status"] != "lost":
                return payload
            if not self._reconstruct_object(hex_id):
                return payload
            # Creating task resubmitted — wait for the new copy.
            if not await self._wait_ready(obj, deadline):
                return {"status": "timeout"}
        return {"status": "lost"}

    def _object_source(self, hex_id: str) -> Optional[dict]:
        """Data-plane span reads: resolve a live servable copy of an object
        to (bulk addr, store name, size) so a consumer can pull just ITS
        span of a block segment over the bulk plane (`data/transport.py`)
        instead of materializing the whole object locally. Read-only; None
        when the object is inline, spilled-only, or unknown (the caller
        falls back to a plain get)."""
        obj = self.objects.get(hex_id)
        if obj is None or obj.status != "ready" or obj.inline is not None:
            return None
        src = self._source_for(obj)
        if src is None or not src.get("bulk") or not src.get("name"):
            return None
        return {"bulk": src["bulk"], "name": src["name"],
                "node": src["node"], "size": obj.size}

    async def h_object_sources(self, conn, meta, msg):
        """Batched _object_source: one RPC resolves every map segment a
        reduce task will read (per-object round trips were measurably the
        whole cost of the transport path on small exchanges)."""
        return {"sources": [self._object_source(h) for h in msg["ids"]]}

    async def h_wait_objects(self, conn, meta, msg):
        ids: List[str] = msg["ids"]
        num_returns: int = msg["num_returns"]
        timeout = msg.get("timeout")
        deadline = None if timeout is None else time.monotonic() + timeout

        def ready_ids():
            return [h for h in ids if self.objects.get(h) and self.objects[h].status == "ready"]

        # Register one event per not-ready object up front; wake on any.
        registered: List[Tuple[ObjectState, asyncio.Event]] = []
        waiters: Dict[asyncio.Task, None] = {}
        try:
            for h in ids:
                obj = self._obj(h)
                if obj.status != "ready":
                    ev = asyncio.Event()
                    obj.events.append(ev)
                    registered.append((obj, ev))
                    waiters[asyncio.ensure_future(ev.wait())] = None
            while True:
                ready = ready_ids()
                if len(ready) >= num_returns or not waiters:
                    return {"ready": ready}
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return {"ready": ready}
                done, _ = await asyncio.wait(
                    list(waiters), timeout=remaining, return_when=asyncio.FIRST_COMPLETED
                )
                if not done:
                    return {"ready": ready_ids()}
                for t in done:
                    waiters.pop(t, None)
        finally:
            for t in waiters:
                t.cancel()
            for obj, ev in registered:
                if ev in obj.events:
                    obj.events.remove(ev)

    async def h_free_objects(self, conn, meta, msg):
        for hex_id in msg["ids"]:
            self._free_object(hex_id)
        return {"ok": True}

    def _drop_copies(self, hex_id: str):
        """Release every physical copy (shm on all nodes + spill file) while
        keeping the directory entry."""
        obj = self.objects.get(hex_id)
        if obj is None:
            return
        for nid, name in list(obj.locations.items()):
            if nid == HEAD_NODE:
                self.store_bytes_used -= obj.size
                self.local_store.release(name, unlink=True)
            else:
                node = self.nodes.get(nid)
                if node is not None and node.alive and node.conn is not None:
                    asyncio.ensure_future(
                        node.conn.send({"type": "free_object", "name": name})
                    )
        obj.locations.clear()
        if obj.spilled_path:
            try:
                os.unlink(obj.spilled_path)
            except OSError:
                pass
            obj.spilled_path = None
        obj.inline = None

    def _free_object(self, hex_id: str):
        self._drop_copies(hex_id)
        obj = self.objects.pop(hex_id, None)
        if obj is not None:
            # Tombstone: replay must not resurrect a directory entry whose
            # arena segment is already unlinked.
            self._wal_append("object_freed", id=hex_id)
        self._gc_candidates.discard(hex_id)
        if obj is not None:
            for h in obj.contains:  # container gone → nested refs unpin
                inner = self.objects.get(h)
                if inner is not None:
                    inner.pinned = max(0, inner.pinned - 1)
                    self._maybe_gc(h)

    # -------------------------------------------------- distributed refcount
    async def h_update_refs(self, conn, meta, msg):
        """Batched 0↔1 ref transitions from one process (reference analog:
        `WaitForRefRemoved` batching via pubsub — `pubsub/README.md:7-27`).
        Adds are processed before releases, so an add+release pair in one
        batch (a short-lived ref) still marks the object ever_held."""
        conn_id = meta.get("conn_id")
        held = self._conn_refs.setdefault(conn_id, set())
        for hex_id in msg.get("add", ()):
            obj = self._obj(hex_id)
            obj.holders.add(conn_id)
            obj.ever_held = True
            held.add(hex_id)
        for hex_id in msg.get("release", ()):
            held.discard(hex_id)
            obj = self.objects.get(hex_id)
            if obj is not None:
                obj.holders.discard(conn_id)
                self._maybe_gc(hex_id)
        return None

    _GC_GRACE = property(lambda self: rt_config.get("gc_grace_s"))
    # must stay > 2× the client flush interval so in-flight adds land

    def _maybe_gc(self, hex_id: str):
        """Schedule a holderless, unpinned object for the GC sweep. The grace
        window absorbs the cross-process handoff race (a receiver's batched
        add-ref can trail the sender's release by up to the flush interval)."""
        obj = self.objects.get(hex_id)
        if (
            obj is None
            or not obj.ever_held
            or obj.holders
            or obj.pinned > 0
            or obj.events
            or obj.dependents
        ):
            return
        if obj.status == "ready" or not obj.expected:
            obj.gc_at = time.monotonic() + self._GC_GRACE
            self._gc_candidates.add(hex_id)

    async def _gc_loop(self):
        while not self._shutdown_event.is_set():
            await asyncio.sleep(rt_config.get("gc_sweep_interval_s"))
            now = time.monotonic()
            for hex_id in list(self._gc_candidates):
                obj = self.objects.get(hex_id)
                if obj is None:
                    self._gc_candidates.discard(hex_id)
                    continue
                if (
                    obj.holders
                    or obj.pinned > 0
                    or obj.events
                    or obj.dependents
                    or not obj.ever_held
                ):
                    self._gc_candidates.discard(hex_id)  # re-added on release
                    continue
                if now < obj.gc_at:
                    continue
                if obj.status == "ready":
                    size = obj.size
                    self._free_object(hex_id)
                    self._event("object_gc", object=hex_id, size=size)
                elif not obj.expected:
                    # Zombie entry (late add after free) — drop the state.
                    self.objects.pop(hex_id, None)
                    self._gc_candidates.discard(hex_id)

    def _pin_args(self, spec: TaskSpec):
        for oid in spec.arg_refs:
            self._obj(oid.hex()).pinned += 1

    def _unpin_args(self, spec: TaskSpec):
        for oid in spec.arg_refs:
            obj = self.objects.get(oid.hex())
            if obj is not None:
                obj.pinned = max(0, obj.pinned - 1)
                self._maybe_gc(oid.hex())

    # ------------------------------------------------------------ spilling
    def _maybe_spill(self):
        """Head-node spill (remote arenas evict via their own LRU)."""
        if self.store_bytes_used <= self.object_store_memory:
            return
        candidates = sorted(
            (
                (o.last_access, h, o)
                for h, o in self.objects.items()
                if o.status == "ready" and HEAD_NODE in o.locations
            ),
        )
        for _, hex_id, obj in candidates:
            if self.store_bytes_used <= self.object_store_memory * 0.8:
                break
            try:
                path = self.local_store.spill(obj.locations[HEAD_NODE], self.spill_dir)
            except FileNotFoundError:
                continue
            self.store_bytes_used -= obj.size
            obj.spilled_path = path
            obj.spilled_node = HEAD_NODE
            del obj.locations[HEAD_NODE]
            self._event("object_spilled", object=hex_id, size=obj.size)

    # --------------------------------------------------------------- tasks
    def _infeasible(self, demand: Dict[str, float]) -> Dict[str, float]:
        """A demand is infeasible iff NO single alive node could ever fit it
        (reference: `ClusterResourceScheduler::IsSchedulableOnNode`)."""
        for n in self.nodes.values():
            if n.alive and all(n.total.get(k, 0.0) >= v for k, v in demand.items()):
                return {}
        return dict(demand)

    def _cluster_totals(self) -> Dict[str, float]:
        total: Dict[str, float] = {}
        for n in self.nodes.values():
            if not n.alive:
                continue
            for k, v in n.total.items():
                total[k] = total.get(k, 0.0) + v
        return total

    async def h_submit_task(self, conn, meta, msg):
        spec: TaskSpec = spec_from_proto_bytes(msg["spec"])
        bad = self._infeasible(spec.resources)
        if bad:
            err = TaskError(
                RuntimeError(
                    f"Task {spec.name} demands {bad} but no node can fit it "
                    f"(cluster total {self._cluster_totals()}) — infeasible, "
                    f"will never schedule."
                ),
                "",
                spec.name,
            )
            for oid in spec.return_ids:
                self._store_error_object(oid.hex(), err)
            return {"ok": False}
        self._pin_args(spec)
        self._remember_lineage(spec)
        self._expect_returns(spec)
        pt = PendingTask(spec=spec, retries_left=spec.options.max_retries)
        self._event(
            "task_submitted", task=spec.task_id.hex(), name=spec.name,
            parent=spec.parent_task_id.hex() if spec.parent_task_id else None,
            trace=spec.trace_id or None,
        )
        self._enqueue(pt)
        self._schedule()
        return {"ok": True}

    def _expect_returns(self, spec: TaskSpec):
        """Create directory entries for a task's returns up front, flagged
        `expected` — distinguishes 'result is coming' from zombie state, and
        guarantees early add-refs land on real entries."""
        for oid in spec.return_ids:
            self._obj(oid.hex()).expected = True

    def _remember_lineage(self, spec: TaskSpec):
        """Keep the creating spec so lost outputs can be re-executed; bounded
        (reference: lineage pinning budget in `reference_count.h`)."""
        self.lineage[spec.task_id.hex()] = spec
        while len(self.lineage) > self._lineage_cap:
            self.lineage.pop(next(iter(self.lineage)))

    def _reconstruct_object(self, hex_id: str) -> bool:
        """Resubmit the creating task of a lost object (reference analog:
        `ObjectRecoveryManager::ReconstructObject`, `object_recovery_manager.cc:141`).
        ObjectID's first 24 bytes ARE the creating TaskID."""
        obj = self.objects.get(hex_id)
        if obj is None or not obj.is_lost():
            return obj is not None
        spec = self.lineage.get(hex_id[:48])
        if spec is None or obj.recon_attempts >= 3:
            return False
        if hex_id not in {oid.hex() for oid in spec.return_ids}:
            # A put() object of that task — re-running would mint a fresh
            # object id, not this one. Not reconstructable (reference has the
            # same rule: only task returns are recoverable).
            return False
        # Deps must be producible first: a GC-freed dep (entry popped, or a
        # zombie pending entry) is re-materialized through ITS lineage before
        # this task is parked waiting on it — else the wait never resolves.
        for oid in spec.arg_refs:
            h = oid.hex()
            dep = self.objects.get(h)
            if dep is None or (dep.status == "pending" and not dep.expected):
                d = self._obj(h)
                d.status = "ready"  # lost-shaped: no copies → is_lost()
                d.inline = None
                d.locations.clear()
                d.spilled_path = None
                if not self._reconstruct_object(h):
                    d.status = "pending"
                    return False
            elif dep.is_lost() and not self._reconstruct_object(h):
                return False
        obj.recon_attempts += 1
        for oid in spec.return_ids:
            o = self._obj(oid.hex())
            self._drop_copies(oid.hex())  # free live siblings before reset
            o.status = "pending"
            o.expected = True
        self._pin_args(spec)
        self._event("object_reconstruction", object=hex_id, task=spec.task_id.hex())
        self._enqueue(PendingTask(spec=spec, retries_left=spec.options.max_retries))
        self._schedule()
        return True

    def _enqueue(self, pt: PendingTask):
        spec = pt.spec
        deps = set()
        for oid in spec.arg_refs:
            h = oid.hex()
            obj = self._obj(h)
            if obj.status != "ready":
                deps.add(h)
                obj.dependents.add(spec.task_id.hex())
        pt.deps_remaining = deps
        if deps:
            self.waiting_tasks[spec.task_id.hex()] = pt
        else:
            self.ready_queue.append(pt)

    def _fits_node(self, node: NodeState, demand: Dict[str, float]) -> bool:
        return node.alive and all(
            node.available.get(k, 0.0) + 1e-9 >= v for k, v in demand.items()
        )

    def _acquire(self, node: NodeState, demand: Dict[str, float]):
        node.last_active = time.monotonic()
        for k, v in demand.items():
            node.available[k] = node.available.get(k, 0.0) - v

    def _release(self, node: NodeState, demand: Dict[str, float]):
        node.last_active = time.monotonic()
        for k, v in demand.items():
            node.available[k] = node.available.get(k, 0.0) + v

    # --- grants may come from node capacity OR a PG bundle reservation ---
    def _grant_apply(self, ws: WorkerState, sign: float):
        """Move ws.assigned into (+1) or out of (-1) its capacity source."""
        if ws.assigned_pg is not None:
            pg_hex, bidx = ws.assigned_pg
            pg = self.pgs.get(pg_hex)
            if pg is not None and bidx < len(pg.get("bundle_avail", [])):
                b = pg["bundle_avail"][bidx]
                for k, v in ws.assigned.items():
                    b[k] = b.get(k, 0.0) + sign * v
        else:
            node = self.nodes.get(ws.node_id)
            if node is not None:
                if sign > 0:
                    self._release(node, ws.assigned)
                else:
                    self._acquire(node, ws.assigned)

    def _grant_release(self, ws: WorkerState):
        self._grant_apply(ws, +1.0)
        ws.assigned = {}
        ws.assigned_pg = None

    def _grant_release_keep(self, ws: WorkerState):
        """Blocked-worker release: free capacity but KEEP ws.assigned/PG so
        worker_unblocked can restore the grant."""
        self._grant_apply(ws, +1.0)

    def _grant_reacquire(self, ws: WorkerState):
        """Inverse of the blocked-release (worker_unblocked)."""
        self._grant_apply(ws, -1.0)

    def _pg_fit(
        self, spec: TaskSpec, strat: PlacementGroupSchedulingStrategy
    ) -> Optional[Tuple[str, int, NodeState]]:
        """Find (pg_hex, bundle_index, node) serving this PG task's demand.
        Reference analog: bundle resources in
        `PlacementGroupResourceManager` (raylet)."""
        pg_obj = strat.placement_group
        pg_hex = pg_obj.id.hex() if hasattr(pg_obj, "id") else str(pg_obj)
        pg = self.pgs.get(pg_hex)
        if pg is None or not pg["ready"]:
            return None
        demand = spec.resources
        idxs = (
            [strat.placement_group_bundle_index]
            if strat.placement_group_bundle_index >= 0
            else range(len(pg["bundles"]))
        )
        for i in idxs:
            if i >= len(pg["bundle_avail"]):
                continue
            avail = pg["bundle_avail"][i]
            if all(avail.get(k, 0.0) + 1e-9 >= v for k, v in demand.items()):
                node = self.nodes.get(pg["bundle_nodes"][i])
                if node is not None and node.alive:
                    return pg_hex, i, node
        return None

    def _idle_worker(
        self, node_id: str, need_tpu: bool = False, cache: Optional[dict] = None,
        env_key: str = "",
    ) -> Optional[WorkerState]:
        if cache is not None:
            # Per-pass index (built once in _schedule): O(1) per lookup
            # instead of an O(workers) scan per queued task per event.
            idx = cache.get("idle")
            if idx is None:
                idx = cache["idle"] = {"cpu": {}, "tpu": {}}
                for ws in self.workers.values():
                    if ws.state == IDLE:
                        kind = "tpu" if ws.has_tpu else "cpu"
                        idx[kind].setdefault(
                            (ws.node_id, ws.env_key), []
                        ).append(ws)
            def take(lst):
                # Validate against live state — entries can go stale if any
                # path mutates workers outside _cache_remove_idle.
                while lst and lst[-1].state != IDLE:
                    lst.pop()
                return lst[-1] if lst else None

            slot = (node_id, env_key)
            if need_tpu:
                return take(idx["tpu"].get(slot) or [])
            got = take(idx["cpu"].get(slot) or [])
            if got is not None:
                return got
            # Fallback: TPU worker takes CPU task (same isolation only).
            return take(idx["tpu"].get(slot) or [])
        fallback = None
        for ws in self.workers.values():
            if ws.state != IDLE or ws.node_id != node_id or ws.env_key != env_key:
                continue
            if need_tpu:
                if ws.has_tpu:
                    return ws
            else:
                # Prefer CPU workers; keep TPU workers free for TPU tasks.
                if not ws.has_tpu:
                    return ws
                fallback = ws
        return None if need_tpu else fallback

    @staticmethod
    def _cache_remove_idle(cache: Optional[dict], ws: WorkerState):
        if cache is None:
            return
        idx = cache.get("idle")
        if idx is None:
            return
        kind = "tpu" if ws.has_tpu else "cpu"
        lst = idx[kind].get((ws.node_id, ws.env_key))
        if not lst:
            return
        if lst[-1] is ws:  # grants take from the tail — O(1) common case
            lst.pop()
        elif ws in lst:
            lst.remove(ws)

    def _candidate_nodes(
        self, spec: TaskSpec, cache: Optional[dict] = None
    ) -> List[NodeState]:
        """Order nodes per the task's scheduling strategy.

        Reference analogs: `HybridSchedulingPolicy` (pack until threshold,
        then least-utilized — `hybrid_scheduling_policy.h:50`),
        `SpreadSchedulingPolicy`, `NodeAffinitySchedulingPolicy`.

        With `cache` (one dict per _schedule pass) the hybrid/sorted
        orderings are computed ONCE per pass, not per queued task per event
        — profiling showed this exact path eating ~85% of controller CPU
        under a deep ready queue (540k calls / 1.6M utilization() evals for
        a 2k-task benchmark).
        """
        strat = spec.options.scheduling_strategy
        if cache is not None and "alive_sorted" in cache:
            alive_sorted = cache["alive_sorted"]
        else:
            alive_sorted = sorted(
                (n for n in self.nodes.values() if n.alive),
                key=lambda n: n.node_id,
            )
            if cache is not None:
                cache["alive_sorted"] = alive_sorted
        if isinstance(strat, NodeAffinitySchedulingStrategy) and strat.node_id:
            pinned = [n for n in alive_sorted if n.node_id == strat.node_id]
            if not strat.soft:
                return pinned
            # Soft-affinity spill follows the HYBRID order, not node-id
            # order: the data plane's locality scorer pins reduce/consumer
            # tasks softly to the node holding their source bytes — when
            # that node is full/dead the task should degrade to the same
            # pack-then-least-utilized policy as default scheduling instead
            # of piling onto whatever node sorts first.
            return pinned + [
                n for n in self._hybrid_order(alive_sorted, cache)
                if n.node_id != strat.node_id
            ]
        if isinstance(strat, NodeLabelSchedulingStrategy):
            # Hard label constraints: only matching nodes are candidates
            # (reference: `NodeLabelSchedulingPolicy`).
            return [
                n for n in alive_sorted
                if all(n.labels.get(k) == str(v) for k, v in strat.hard.items())
            ]
        if isinstance(strat, SpreadSchedulingStrategy):
            # True round-robin: each spread decision starts one node further
            # along, so consecutive tasks land on distinct nodes (reference:
            # `SpreadSchedulingPolicy` round-robins over FEASIBLE nodes).
            # Nodes that can never hold the demand (a 0-CPU head) are left
            # out of the rotation — rotating onto one silently re-packs its
            # share onto whichever node sorts next, skewing the spread.
            feasible = [
                n for n in alive_sorted
                if all(n.total.get(k, 0.0) >= v
                       for k, v in spec.resources.items())
            ] or alive_sorted
            self._spread_rr += 1
            r = self._spread_rr % len(feasible) if feasible else 0
            return feasible[r:] + feasible[:r]
        # Hybrid default: pack in node-id order while below the utilization
        # threshold, then least-utilized.
        return self._hybrid_order(alive_sorted, cache)

    def _hybrid_order(
        self, alive_sorted: List[NodeState], cache: Optional[dict]
    ) -> List[NodeState]:
        """Pack-until-threshold then least-utilized (reference:
        `hybrid_scheduling_policy.h:50`), cached once per schedule pass."""
        if cache is not None and "hybrid" in cache:
            return cache["hybrid"]
        packable = [n for n in alive_sorted if n.utilization() < 0.8]
        rest = sorted(
            (n for n in alive_sorted if n.utilization() >= 0.8),
            key=lambda n: n.utilization(),
        )
        out = packable + rest
        if cache is not None:
            cache["hybrid"] = out
        return out

    def _deps_payload(self, spec: TaskSpec, node_id: str) -> dict:
        locs = {}
        for oid in spec.arg_refs:
            h = oid.hex()
            locs[h] = self._location_payload(self.objects[h], node_id)
        return locs

    async def _dispatch(self, node: NodeState, ws: WorkerState, pt: PendingTask):
        """Send a task to its granted worker, first materializing remote deps
        on that worker's node (controller-directed pull)."""
        spec = pt.spec
        task_hex = spec.task_id.hex()
        if spec.task_type == TaskType.ACTOR_CREATION_TASK and spec.actor_id:
            astate = self.actors.get(spec.actor_id.hex())
            if (
                astate is not None
                and astate.state == "alive"
                and astate.worker_id
                and astate.worker_id != ws.worker_id
            ):
                # Failover race resolved in the actor's favor: restore
                # requeued this creation (it looked never-started), but the
                # surviving worker re-adopted first. Dropping here is what
                # keeps the chaos gate's "zero duplicated actors" honest.
                self.running.pop(task_hex, None)
                ws.state = IDLE
                ws.current_task = None
                ws.actor_hex = None
                self._grant_release(ws)
                self._unpin_args(spec)
                self._event("actor_recreate_dropped", actor=spec.actor_id.hex())
                self._schedule()
                return
        try:
            await asyncio.gather(
                *(self._ensure_local(node.node_id, oid.hex()) for oid in spec.arg_refs)
            )
        except Exception as e:  # noqa: BLE001
            # A dep's every copy died mid-transfer. Return the grant, then
            # try lineage reconstruction before declaring the task failed.
            self.running.pop(task_hex, None)
            was_actor = ws.state == ACTOR
            ws.state = IDLE
            ws.current_task = None
            ws.actor_hex = None
            self._grant_release(ws)
            lost = [
                oid.hex()
                for oid in spec.arg_refs
                if (o := self.objects.get(oid.hex())) is not None and o.is_lost()
            ]
            if lost and all(self._reconstruct_object(h) for h in lost):
                # Deps are re-executing; requeue — _enqueue re-registers the
                # (now pending) deps so the task waits for the new copies.
                if was_actor and spec.actor_id is not None:
                    astate = self.actors.get(spec.actor_id.hex())
                    if astate is not None:
                        self._set_actor_state(astate, "pending")
                self._event("task_requeued_for_reconstruction", task=task_hex)
                self._enqueue(pt)
                self._schedule()
                return
            err = TaskError(
                RuntimeError(f"dependency transfer failed: {e}"), "", spec.name
            )
            self._unpin_args(spec)
            if was_actor and spec.actor_id is not None:
                astate = self.actors.get(spec.actor_id.hex())
                if astate is not None:
                    astate.init_error = err
                    self._set_actor_state(astate, "dead")
                    self._drain_actor_queue(astate, err)
            for oid in spec.return_ids:
                self._store_error_object(oid.hex(), err)
            self._schedule()
            return
        msg_type = (
            "create_actor"
            if spec.task_type == TaskType.ACTOR_CREATION_TASK
            else "execute_task"
        )
        try:
            # post(): batched fire-and-forget — a dispatch burst rides one
            # writer wake-up; a dead conn raises and the worker-death path
            # (already in flight via on_close) requeues from self.running.
            ws.conn.post(
                {
                    "type": msg_type,
                    "spec": spec_to_proto_bytes(spec),
                    "deps": self._deps_payload(spec, node.node_id),
                }
            )
        except ConnectionError:
            return
        self._event("task_dispatched", task=task_hex, worker=ws.worker_id,
                     node=node.node_id)

    # ----------------------------------------------- two-level scheduling
    def _handoff_cap(self, node: NodeState) -> int:
        return max(
            int(node.total.get("CPU", 0)), 1
        ) * rt_config.get("local_dispatch_depth")

    def _try_handoff(self, pt: PendingTask, preferred: Optional[NodeState]) -> bool:
        """Hand a queued plain task to a node agent's LocalDispatcher
        instead of keeping it head-resident (reference: ClusterTaskManager
        node pick + spillback of the QUEUE, not just of running tasks).

        Only the overflow path takes this: tasks that found an idle worker
        were dispatched centrally already, so agents receive exactly the
        backlog — the population whose dispatch otherwise serializes
        through this loop."""
        spec = pt.spec
        if not rt_config.get("local_dispatch"):
            return False
        if spec.task_type != TaskType.NORMAL_TASK:
            return False
        if _task_env_key(spec):
            return False  # isolated tasks need env-keyed workers, not leases
        demand = spec.resources
        # The dispatcher executes on generic CPU:1 leases — only tasks whose
        # demand a CPU:1 lease actually covers may ride the plane. Custom
        # resources / multi-CPU shapes keep central accounting (which debits
        # node.available per task).
        if any(k != "CPU" for k in demand) or demand.get("CPU", 0) > 1:
            return False
        strat = spec.options.scheduling_strategy
        if not isinstance(
            strat,
            (DefaultSchedulingStrategy, SpreadSchedulingStrategy,
             NodeAffinitySchedulingStrategy),
        ):
            return False
        if isinstance(strat, NodeAffinitySchedulingStrategy):
            node = self.nodes.get(strat.node_id)
            candidates = [node] if node is not None else []
        elif pt.pinned_node is not None:
            node = self.nodes.get(pt.pinned_node)
            candidates = [node] if node is not None else []
        elif preferred is not None:
            candidates = [preferred] + [
                n for n in self.nodes.values() if n is not preferred
            ]
        else:
            candidates = list(self.nodes.values())
        best = None
        for node in candidates:
            if (
                node is None or not node.alive or node.conn is None
                or not node.dispatch
                or node.handoff_inflight >= self._handoff_cap(node)
                # The dispatcher executes on CPU:1 leases — a node that can
                # never grant one (e.g. TPU-only, CPU:0) would strand even
                # num_cpus=0 tasks in 10s spill-back bounces.
                or node.total.get("CPU", 0) < 1
                or not all(node.total.get(k, 0) >= v for k, v in demand.items())
            ):
                continue
            if best is None or node.handoff_inflight < best.handoff_inflight:
                best = node
            if node is preferred or pt.pinned_node is not None:
                break  # placement-constrained: first viable wins
        if best is None:
            return False
        task_hex = spec.task_id.hex()
        self.running[task_hex] = (f"@{best.node_id}", pt)
        best.handoff_inflight += 1
        self._event("task_handoff", task=task_hex, node=best.node_id)
        if not spec.arg_refs:
            try:
                best.conn.post({
                    "type": "enqueue_task", "task": task_hex,
                    "spec": spec_to_proto_bytes(spec), "deps": {},
                })
            except Exception:  # noqa: BLE001 — conn died before alive flipped
                self.running.pop(task_hex, None)
                best.handoff_inflight = max(0, best.handoff_inflight - 1)
                return False
        else:
            asyncio.ensure_future(self._handoff_send(best, pt))
        return True

    async def _handoff_send(self, node: NodeState, pt: PendingTask):
        """Materialize args on the target node, then ship spec+deps — the
        agent dispatches with zero further head involvement."""
        spec = pt.spec
        task_hex = spec.task_id.hex()
        try:
            await asyncio.gather(
                *(self._ensure_local(node.node_id, oid.hex())
                  for oid in spec.arg_refs)
            )
            if task_hex in self.cancelled:
                # ray.cancel() landed while deps were in flight: h_cancel's
                # cancel_task post found nothing at the agent (the enqueue
                # hadn't shipped), so suppress the enqueue here or the task
                # would run uncancellably.
                self.running.pop(task_hex, None)
                node.handoff_inflight = max(0, node.handoff_inflight - 1)
                self._finish_cancelled(pt)
                self._schedule()
                return
            node.conn.post({
                "type": "enqueue_task", "task": task_hex,
                "spec": spec_to_proto_bytes(spec),
                "deps": self._deps_payload(spec, node.node_id),
            })
        except Exception as e:  # noqa: BLE001 — dep transfer / conn failure
            if self.running.pop(task_hex, None) is None:
                # Ownership already taken (node death requeued/retried the
                # task, or cancel finished it) — failing the returns here
                # would poison a retry that may yet succeed.
                return
            node.handoff_inflight = max(0, node.handoff_inflight - 1)
            lost = [
                oid.hex()
                for oid in spec.arg_refs
                if (o := self.objects.get(oid.hex())) is not None and o.is_lost()
            ]
            if lost and all(self._reconstruct_object(h) for h in lost):
                self._event("task_requeued_for_reconstruction", task=task_hex)
                self._enqueue(pt)
            else:
                err = TaskError(
                    RuntimeError(f"dependency transfer failed: {e}"), "",
                    spec.name,
                )
                self._unpin_args(spec)
                if spec.num_returns == -1:
                    self._fail_stream(spec, err)
                for oid in spec.return_ids:
                    self._store_error_object(oid.hex(), err)
            self._schedule()

    def _retry_or_fail(self, pt: PendingTask, task_hex: str, cause: str):
        """Shared worker-loss policy: consume a retry and requeue, else fail
        the returns (used by _on_worker_death and agent-reported losses)."""
        if task_hex in self.cancelled:
            self._finish_cancelled(pt)
            return
        if pt.retries_left > 0:
            pt.retries_left -= 1
            pt.spec.attempt_number += 1
            pt.pinned_node = None
            self._event("task_retry", task=task_hex)
            self._enqueue(pt)
            return
        err = TaskError(WorkerCrashedError(cause), "", pt.spec.name)
        self._unpin_args(pt.spec)
        if pt.spec.num_returns == -1:
            self._fail_stream(pt.spec, err)
        for oid in pt.spec.return_ids:
            self._store_error_object(oid.hex(), err)

    async def h_worker_spawn_failed(self, conn, meta, msg):
        """Agent couldn't even exec the isolated worker command (missing
        conda/podman) — fail the tasks waiting on that env."""
        worker_id = msg["worker_id"]
        key = self._worker_env_keys.get(worker_id, "")
        node = self.nodes.get(meta.get("node_id", ""))
        if node is not None and key:
            self._iso_spawn_failed(
                node, worker_id, {"key": key},
                msg.get("error", "spawn failed"), tpu=bool(msg.get("tpu")),
            )
            self._schedule()
        return None

    async def h_agent_task_lost(self, conn, meta, msg):
        """Agent-side dispatch saw the executing worker die (local worker
        loss is AGENT-observed for handed-off tasks — the head never granted
        that worker)."""
        entry = self.running.pop(msg["task"], None)
        if entry is None:
            return None
        node = self.nodes.get(meta.get("node_id", ""))
        if node is not None:
            node.handoff_inflight = max(0, node.handoff_inflight - 1)
        self._retry_or_fail(
            entry[1], msg["task"],
            f"Worker {msg.get('worker_id', '?')} died executing task",
        )
        self._schedule()
        return None

    async def h_agent_spillback(self, conn, meta, msg):
        """Agent could not serve queued tasks (no leases obtainable) — they
        come home for central placement (reference: spillback,
        `cluster_task_manager.h` ScheduleOnNode fallback)."""
        node = self.nodes.get(meta.get("node_id", ""))
        for task_hex in msg.get("tasks", []):
            entry = self.running.pop(task_hex, None)
            if entry is None:
                continue
            if node is not None:
                node.handoff_inflight = max(0, node.handoff_inflight - 1)
            pt = entry[1]
            pt.pinned_node = None
            if task_hex in self.cancelled:
                self._finish_cancelled(pt)
            else:
                self._enqueue(pt)
        self._schedule()
        return None

    async def h_agent_task_cancelled(self, conn, meta, msg):
        entry = self.running.pop(msg["task"], None)
        node = self.nodes.get(meta.get("node_id", ""))
        if node is not None:
            node.handoff_inflight = max(0, node.handoff_inflight - 1)
        if entry is not None:
            self._finish_cancelled(entry[1])
        return None

    def _schedule(self):
        """Request a scheduling pass, coalesced per event-loop drain.

        Deferral is the controller's lifecycle batching: every message in
        one socket read burst (a registration storm, a task_done wave) maps
        to ONE pass via call_soon instead of a pass per message. Callers
        observe the same semantics — handlers are async, so dispatch was
        never synchronous with the triggering message anyway.
        """
        if self._schedule_soon:
            return
        self._schedule_soon = True
        try:
            asyncio.get_running_loop().call_soon(self._schedule_tick)
        except RuntimeError:
            # No running loop (unit tests poking controller state
            # synchronously) — run the pass inline like the old path did.
            self._schedule_soon = False
            self._schedule_now()

    def _schedule_tick(self):
        self._schedule_soon = False
        self._schedule_now()

    def _schedule_now(self):
        """Run scheduling passes until quiescent.

        NON-REENTRANT: failure paths inside a pass (_fail_task →
        _mark_ready) call _schedule again; a nested pass would grant workers
        the outer pass still holds in its per-pass idle cache (double-grant).
        Nested calls just flag a rerun.
        """
        if self._scheduling:
            self._schedule_again = True
            return
        self._scheduling = True
        try:
            while True:
                self._schedule_again = False
                self._schedule_pass()
                if not self._schedule_again:
                    break
        finally:
            self._scheduling = False

    def _schedule_pass(self):
        """One scheduling pass (reference analog:
        `ClusterTaskManager::ScheduleAndDispatchTasks` (node pick) +
        `LocalTaskManager` (worker grant), collapsed)."""
        # Pending PGs first: capacity freed since the last pass may fit them
        # (reference: `SchedulePendingPlacementGroups` on resource change).
        if any(not pg["ready"] for pg in self.pgs.values()):
            self._retry_pending_pgs()
        made_progress = True
        # Per-pass scheduler cache: node orderings + idle-worker index
        # (invalidated per grant via _cache_remove_idle).
        cache: Dict[str, Any] = {}
        # Demand signatures that found NO capacity this pass: capacity only
        # shrinks within a pass, so identical demands behind them can skip
        # the node scan entirely (the dominant cost with a deep homogeneous
        # queue — profiling showed 800k _fits_node calls for a 3k-task run).
        # Value = node to aim a spawn hint at (None if infeasible everywhere).
        no_capacity: Dict[tuple, Optional[str]] = {}
        # node_id -> CPU workers wanted this pass; flushed bounded below so a
        # task waiting out a worker boot doesn't fork one per scheduling event.
        spawn_wanted: Dict[str, int] = {}
        # Actor creations wanting a worker — flushed with force=True (the
        # task-pool cap must not deadlock actor creation; each actor owns a
        # dedicated process).
        spawn_wanted_actors: Dict[str, int] = {}
        while made_progress and self.ready_queue:
            made_progress = False
            # Bounded head scan: dispatch FIFO, skipping over at most a small
            # window of blocked tasks (so a TPU task at the head can't starve
            # CPU tasks behind it, but a long queue isn't rescanned per event).
            scan = min(len(self.ready_queue), rt_config.get("scheduler_scan_window"))
            for _ in range(scan):
                if not self.ready_queue:  # prefetch may consume entries mid-scan
                    break
                pt = self.ready_queue.popleft()
                spec = pt.spec
                if spec.task_id.hex() in self.cancelled:
                    self._finish_cancelled(pt)
                    made_progress = True
                    continue
                demand = spec.resources
                need_tpu = demand.get("TPU", 0) > 0
                env_key = _task_env_key(spec)
                chosen: Optional[Tuple[NodeState, WorkerState]] = None
                spawn_on: Optional[NodeState] = None
                pg_grant: Optional[Tuple[str, int]] = None
                strat = spec.options.scheduling_strategy
                if (
                    isinstance(strat, PlacementGroupSchedulingStrategy)
                    and strat.placement_group is not None
                ):
                    pg_obj = strat.placement_group
                    pg_state = self.pgs.get(
                        pg_obj.id.hex() if hasattr(pg_obj, "id") else str(pg_obj)
                    )
                    hard_fail = None
                    if pg_state is None:
                        hard_fail = "placement group was removed"
                    else:
                        bidx0 = strat.placement_group_bundle_index
                        idxs = (
                            [bidx0] if bidx0 >= 0 else range(len(pg_state["bundles"]))
                        )
                        if not any(
                            i < len(pg_state["bundles"])
                            and all(
                                pg_state["bundles"][i].get(k, 0.0) >= v
                                for k, v in demand.items()
                            )
                            for i in idxs
                        ):
                            hard_fail = (
                                f"demand {demand} exceeds the bundle capacity"
                            )
                    if hard_fail is not None:
                        self._fail_task(
                            pt,
                            TaskError(
                                RuntimeError(
                                    f"Task {spec.name} cannot schedule: {hard_fail}."
                                ),
                                "",
                                spec.name,
                            ),
                        )
                        made_progress = True
                        continue
                    fit = self._pg_fit(spec, strat)
                    if fit is None:
                        self.ready_queue.append(pt)  # bundle busy / placing
                        continue
                    pg_hex, bidx, node = fit
                    ws = self._idle_worker(node.node_id, need_tpu, cache, env_key)
                    if ws is None:
                        self.ready_queue.append(pt)
                        if env_key:
                            self._spawn_isolated(node, spec, tpu=need_tpu)
                        elif need_tpu:
                            self._spawn_worker(tpu=True, node=node)
                        else:
                            target = (
                                spawn_wanted_actors
                                if spec.task_type == TaskType.ACTOR_CREATION_TASK
                                else spawn_wanted
                            )
                            target[node.node_id] = (
                                target.get(node.node_id, 0) + 1
                            )
                        continue
                    avail = self.pgs[pg_hex]["bundle_avail"][bidx]
                    for k, v in demand.items():
                        avail[k] = avail.get(k, 0.0) - v
                    pg_grant = (pg_hex, bidx)
                    chosen = (node, ws)
                else:
                    # Spread/affinity COMMIT to the placement-correct node
                    # (spawn a worker there and wait); hybrid falls through to
                    # any node with an idle worker — packing tolerates it.
                    commit_first_fit = isinstance(
                        strat,
                        (SpreadSchedulingStrategy, NodeAffinitySchedulingStrategy),
                    )
                    sig = pt.sched_sig(need_tpu)
                    if sig is not None and sig in no_capacity:
                        # Same demand already found no central capacity this
                        # pass — the agent handoff plane is exactly for this
                        # backlog population.
                        hint_node = (
                            self.nodes.get(no_capacity[sig])
                            if no_capacity[sig] is not None else None
                        )
                        if self._try_handoff(pt, hint_node):
                            made_progress = True
                            continue
                        self.ready_queue.append(pt)
                        hint = no_capacity[sig]
                        if hint is not None and env_key:
                            hn = self.nodes.get(hint)
                            if hn is not None:
                                self._spawn_isolated(hn, spec, tpu=need_tpu)
                        elif hint is not None and not need_tpu:
                            target = (
                                spawn_wanted_actors
                                if spec.task_type == TaskType.ACTOR_CREATION_TASK
                                else spawn_wanted
                            )
                            target[hint] = target.get(hint, 0) + 1
                        continue
                    if pt.pinned_node is not None:
                        pin = self.nodes.get(pt.pinned_node)
                        candidates = [pin] if pin is not None and pin.alive else None
                        if candidates is None:
                            pt.pinned_node = None  # pinned node died — re-pick
                            candidates = self._candidate_nodes(spec, cache)
                    else:
                        candidates = self._candidate_nodes(spec, cache)
                    for node in candidates:
                        if not self._fits_node(node, demand):
                            continue
                        ws = self._idle_worker(node.node_id, need_tpu, cache, env_key)
                        if ws is None:
                            spawn_on = spawn_on or node
                            if commit_first_fit:
                                pt.pinned_node = node.node_id
                                break
                            continue
                        chosen = (node, ws)
                        break
                    if chosen is None:
                        if self._try_handoff(pt, spawn_on):
                            made_progress = True
                            continue
                        self.ready_queue.append(pt)
                        if sig is not None:
                            no_capacity[sig] = (
                                spawn_on.node_id if spawn_on is not None else None
                            )
                        if spawn_on is not None:
                            if env_key:
                                self._spawn_isolated(spawn_on, spec, tpu=need_tpu)
                            elif need_tpu:
                                self._spawn_worker(tpu=True, node=spawn_on)
                            else:
                                target = (
                                    spawn_wanted_actors
                                    if spec.task_type
                                    == TaskType.ACTOR_CREATION_TASK
                                    else spawn_wanted
                                )
                                target[spawn_on.node_id] = (
                                    target.get(spawn_on.node_id, 0) + 1
                                )
                        continue
                    node, ws = chosen
                    self._acquire(node, demand)
                node, ws = chosen
                self._cache_remove_idle(cache, ws)
                ws.assigned = dict(demand)
                ws.assigned_pg = pg_grant
                task_hex = spec.task_id.hex()
                self.running[task_hex] = (ws.worker_id, pt)
                if spec.task_type == TaskType.ACTOR_CREATION_TASK:
                    ws.state = ACTOR
                    ws.actor_hex = spec.actor_id.hex()
                else:
                    ws.state = BUSY
                    ws.current_task = task_hex
                asyncio.ensure_future(self._dispatch(node, ws, pt))
                self._maybe_prefetch(ws, node, pt, cache)
                made_progress = True
        # One pass over the worker table serves every spawn decision below
        # (per-call scans dominated profiles at 58k _spawn_worker calls).
        # In-flight boots are tracked by node.spawning / the spawn ledger —
        # registered workers are never in STARTING state, so only the live
        # count needs the table walk.
        live_by_node: Dict[str, int] = {}
        if spawn_wanted or spawn_wanted_actors or self.ready_queue:
            for w in self.workers.values():
                if w.state in (DEAD, ACTOR):
                    continue  # task-pool occupancy only (see _spawn_worker)
                live_by_node[w.node_id] = live_by_node.get(w.node_id, 0) + 1
        # Flush per-node spawn demand, net of workers already booting there
        # (reference analog: worker_pool PrestartWorkers on backlog hints,
        # `worker_pool.h:354` — backlog-sized, not one-per-event).
        for forced, wants in ((False, spawn_wanted), (True, spawn_wanted_actors)):
            for node_id, wanted in wants.items():
                node = self.nodes.get(node_id)
                if node is None or not node.alive:
                    continue
                booting = node.spawning
                for _ in range(
                    max(0, min(wanted - booting, rt_config.get("spawn_burst_cap")))
                ):
                    # node.spawning increments per spawn — in-loop spawns are
                    # already counted; adding i here double-counted them.
                    self._spawn_worker(
                        node=node,
                        live_count=live_by_node.get(node_id, 0),
                        force=forced,
                    )
        # Top the head pool up to the queue depth.
        starting = self.head.spawning
        # Exact CPU-backlog count is O(queue); bound the scan to the first
        # 256 entries — an UNDERestimate for deeper queues (spawning catches
        # up as the queue drains), and still exactly 0 for TPU-only queues
        # (counting those as CPU would ratchet useless head workers up to
        # the pool cap).
        cpu_backlog = sum(
            1 for pt in itertools.islice(self.ready_queue, 256)
            if pt.spec.resources.get("TPU", 0) == 0
            # Actor creations get FORCED dedicated spawns above — counting
            # them here pre-forks pool workers nothing will ever run on
            # (observed: ~30 junk forks per 100-actor burst).
            and pt.spec.task_type != TaskType.ACTOR_CREATION_TASK
        )
        deficit = cpu_backlog - starting
        head_live = live_by_node.get(self.head.node_id, 0)
        for _ in range(max(0, min(deficit, rt_config.get("worker_prestart_cap")))):
            self._spawn_worker(live_count=head_live)
        self._reclaim_stranded_prefetches()
        self._revoke_leases_for_backlog()

    def _reclaim_stranded_prefetches(self):
        """Un-strand prefetched tasks: a task pipelined behind a busy worker
        (_maybe_prefetch) waits on that worker's current task — if a worker
        that could actually RUN it has since gone idle, ask the busy worker
        to give the un-started spec back. The protocol is event-driven (no
        timeouts, no ambiguity): the reclaim is a one-way push; the worker
        answers with its own `task_dropped` push only if the drop beat
        execution (h_task_dropped requeues), else its `task_done` arrives as
        usual and the reclaim dissolves."""
        if not self._prefetch_ids:
            return
        pending = []
        for wid in list(self._prefetch_ids):
            ws = self.workers.get(wid)
            if ws is None or ws.prefetch_task is None:
                self._prefetch_ids.discard(wid)  # self-cleaning
                continue
            if ws.reclaiming_task is None and ws.conn is not None:
                pending.append(ws)
        if not pending:
            return
        idle = [
            w for w in self.workers.values()
            if w.state == IDLE and w.conn is not None
        ]
        if not idle:
            return
        for ws in pending:
            if not idle:
                break
            entry = self.running.get(ws.prefetch_task)
            if entry is None:
                continue
            demand = entry[1].spec.resources
            need_tpu = demand.get("TPU", 0) > 0
            # Reclaiming only helps if some idle worker can take the task NOW
            # (TPU-capability match + node capacity) — otherwise the task
            # would lose its guaranteed next-in-line slot for nothing. Each
            # matched idle worker is consumed so at most idle-capacity-many
            # prefetches are pulled back per pass.
            match = next(
                (
                    w for w in idle
                    if (w.has_tpu or not need_tpu)
                    and w.node_id in self.nodes
                    and self._fits_node(self.nodes[w.node_id], demand)
                ),
                None,
            )
            if match is None:
                continue
            idle.remove(match)
            ws.reclaiming_task = ws.prefetch_task
            asyncio.ensure_future(self._send_reclaim(ws, ws.prefetch_task))

    async def _send_reclaim(self, ws: WorkerState, task_hex: str):
        try:
            await ws.conn.send({"type": "reclaim_task", "task": task_hex})
        except Exception:  # noqa: BLE001 — worker dying; death path requeues
            ws.reclaiming_task = None

    async def h_task_dropped(self, conn, meta, msg):
        """The worker dropped a reclaimed prefetch before executing it.
        Worker→controller FIFO means any task_done(current) sorted before
        this, so exactly two worker states are possible: the task is still
        prefetch-pending, or it was promoted to current (in which case the
        worker is actually idle — it skipped the spec)."""
        task_hex = msg["task"]
        ws = self.workers.get(meta["worker_id"]) if meta.get("worker_id") else None
        if ws is not None:
            if ws.reclaiming_task == task_hex:
                ws.reclaiming_task = None
            if ws.prefetch_task == task_hex:
                ws.prefetch_task = None
            elif ws.current_task == task_hex and ws.state == BUSY:
                ws.state = IDLE
                ws.current_task = None
                self._grant_release(ws)
        entry = self.running.pop(task_hex, None)
        if entry is None:
            return None
        if task_hex in self.cancelled:
            self._finish_cancelled(entry[1])
        else:
            self.ready_queue.appendleft(entry[1])  # it was the FIFO head
            self._event(
                "task_reclaimed", task=task_hex,
                worker=ws.worker_id if ws is not None else "",
            )
        self._schedule()
        return None

    # ------------------------------------------------- direct task plane
    # Reference analog: `direct_task_transport.cc:135-247` — submitters hold
    # cached worker leases and push task specs straight to the leased worker
    # (PushNormalTask), touching the scheduler only for grant/return. Here
    # the controller additionally stays out of the RESULT path: small
    # results return inline over the submitter↔worker socket.
    async def h_request_lease(self, conn, meta, msg):
        demand = {k: float(v) for k, v in (msg.get("resources") or {}).items()}
        need_tpu = demand.get("TPU", 0) > 0
        count = max(1, min(int(msg.get("count", 1)), 16))
        # PARK until at least one grant or the deadline: a cold pool takes a
        # spawn round (~0.5s) to produce grantable workers — client-side
        # retry backoff turned that into multi-second task latency.
        deadline = time.monotonic() + min(float(msg.get("wait_s", 8.0)), 30.0)
        bkey = tuple(sorted(demand.items()))
        first = True
        # LocalDispatchers lease only their OWN node's workers (the point of
        # the handoff is node-local dispatch); submitters lease anywhere.
        node_filter = msg.get("node_id")
        while True:
            grants = self._try_grant_leases(
                meta, demand, need_tpu, count, spawn=first,
                node_filter=node_filter,
            )
            first = False
            if grants or time.monotonic() >= deadline:
                break
            # The PARKED demand is autoscaler load — record it now, not
            # after the park (scale-up is what un-parks a full cluster).
            self._lease_backlog[bkey] = (demand, count, time.monotonic())
            try:
                await asyncio.wait_for(self._worker_arrival.wait(), 0.25)
            except (asyncio.TimeoutError, TimeoutError):
                pass
        # Feed the autoscaler load metrics (drivers re-request while
        # buffered, refreshing the entry; satisfied requests clear it).
        if len(grants) < count:
            self._lease_backlog[bkey] = (demand, count - len(grants), time.monotonic())
        else:
            self._lease_backlog.pop(bkey, None)
        if grants:
            self._wal_append(
                "lease_granted",
                workers=[g["worker_id"] for g in grants],
                holder=meta.get("conn_id") or 0,
            )
            self._event("lease_granted", n=len(grants), holder=meta.get("conn_id"))
        return {"leases": grants}

    def _try_grant_leases(self, meta, demand, need_tpu, count, spawn=True,
                          node_filter=None):
        grants = []
        spawn_hint: Optional[NodeState] = None
        # One idle-worker index per grant call: the uncached scan was
        # O(workers) per requested lease — a wave of 5k resident actor
        # workers made every lease request pay a full table walk.
        cache: Dict[str, Any] = {}
        for _ in range(count):
            got = None
            for node in self.nodes.values():
                if node_filter is not None and node.node_id != node_filter:
                    continue
                if not self._fits_node(node, demand):
                    continue
                ws = self._idle_worker(node.node_id, need_tpu, cache)
                if ws is None:
                    spawn_hint = spawn_hint or node
                    continue
                if not ws.direct_addr:
                    continue
                got = (node, ws)
                break
            if got is None:
                break
            node, ws = got
            self._acquire(node, demand)
            ws.assigned = dict(demand)
            ws.state = LEASED
            self._leased_ids.add(ws.worker_id)
            ws.leased_to = meta.get("conn_id")
            meta.setdefault("leases", set()).add(ws.worker_id)
            grants.append({"worker_id": ws.worker_id, "addr": ws.direct_addr})
        if spawn and len(grants) < count and spawn_hint is not None and not need_tpu:
            # Under-supplied: top the pool up, NET of workers already
            # booting (unbounded bursts per grow probe were a spawn storm —
            # each booting interpreter costs ~2s of CPU).
            want = count - len(grants) - spawn_hint.spawning
            for _ in range(
                max(0, min(want, rt_config.get("spawn_burst_cap")))
            ):
                self._spawn_worker(node=spawn_hint)
        return grants

    def _release_lease(self, ws: WorkerState, requeue: bool = True):
        if ws.state != LEASED:
            return
        self._leased_ids.discard(ws.worker_id)
        if ws.blocked:
            # Capacity already released at block time (h_worker_blocked) —
            # releasing again would double-credit the node.
            ws.assigned = {}
            ws.assigned_pg = None
            ws.blocked = False
        else:
            self._grant_release(ws)
        ws.state = IDLE
        ws.leased_to = None
        ws.revoking = False
        if requeue:
            self._schedule()

    async def h_return_lease(self, conn, meta, msg):
        self._return_one_lease(meta, msg["worker_id"])
        return {"ok": True}

    async def h_return_lease_batch(self, conn, meta, msg):
        """Batched give-back from a holder's idle sweep — one frame, one
        scheduling request for the whole set."""
        for worker_id in msg.get("worker_ids", ()):
            self._return_one_lease(meta, worker_id)
        return None

    def _return_one_lease(self, meta, worker_id: str):
        ws = self.workers.get(worker_id)
        leases = meta.get("leases")
        if leases is not None:
            leases.discard(worker_id)
        if ws is not None and ws.leased_to == meta.get("conn_id"):
            self._wal_append("lease_returned", worker=worker_id)
            self._release_lease(ws)

    def _revoke_leases_for_backlog(self):
        """Queued work + zero placement → pull leases back (the holder
        drains in-flight pushes and returns). Prevents idle-leased workers
        from starving the queued path."""
        if not self.ready_queue or not self._leased_ids:
            return
        for wid in list(self._leased_ids):
            ws = self.workers.get(wid)
            if ws is None or ws.state != LEASED or ws.revoking or ws.leased_to is None:
                continue
            holder = self._conns_by_id.get(ws.leased_to)
            if holder is None:
                self._release_lease(ws)
                continue
            ws.revoking = True
            asyncio.ensure_future(self._send_revoke(holder, ws))

    async def _send_revoke(self, holder: Connection, ws: WorkerState):
        try:
            await holder.send({"type": "revoke_lease", "worker_id": ws.worker_id})
        except Exception:  # noqa: BLE001 — holder dying; disconnect cleans up
            pass

    # -------------------------------------------- direct actor call plane
    # Reference analog: direct actor call transport — after creation, actor
    # calls flow submitter→actor-worker without the GCS/raylet in the loop.
    # The handoff FENCE threads through the same controller→worker FIFO as
    # queued classic calls, so direct mode starts only after every prior
    # classic call is already in the worker's queue (ordering preserved).
    async def h_actor_handoff(self, conn, meta, msg):
        astate = self.actors.get(msg["actor"])
        if astate is None or astate.state == "dead":
            return {"ok": False, "reason": "actor not alive"}
        token = f"{msg['actor']}:{next(self._handoff_counter)}"
        fut = asyncio.get_running_loop().create_future()
        self._handoff_waiters[token] = fut
        # The fence rides the actor's ORDERED send queue (_pump_actor), so
        # every classic call submitted before it — including calls still
        # waiting on args or on actor creation — reaches the worker first.
        self._shard_enqueue(astate, _HandoffFence(token))
        try:
            await asyncio.wait_for(fut, timeout=msg.get("timeout", 30))
        except Exception:  # noqa: BLE001 — worker busy/dead; caller stays classic
            return {"ok": False, "reason": "handoff timed out"}
        finally:
            self._handoff_waiters.pop(token, None)
        ws = self.workers.get(astate.worker_id)
        if astate.state != "alive" or ws is None or not ws.direct_addr:
            return {"ok": False, "reason": "actor not alive"}
        return {"ok": True, "addr": ws.direct_addr, "worker_id": ws.worker_id}

    async def h_handoff_ready(self, conn, meta, msg):
        fut = self._handoff_waiters.get(msg["token"])
        if fut is not None and not fut.done():
            fut.set_result(True)
        return None

    def _resolve_handoff_failed(self, token: str):
        """Main-loop: answer a handoff waiter whose fence met a dead actor
        (h_actor_handoff re-checks liveness after the future resolves, so a
        False here yields its not-alive reply)."""
        fut = self._handoff_waiters.get(token)
        if fut is not None and not fut.done():
            fut.set_result(False)

    def _maybe_prefetch(
        self,
        ws: WorkerState,
        node: NodeState,
        pt: PendingTask,
        cache: Optional[dict] = None,
    ):
        """Queue ONE more same-shape task behind the one just dispatched
        (reference: lease reuse — steady-state same-shape submission skips
        the raylet, `direct_task_transport.cc:135-247`). Only argless,
        non-streaming, non-PG NORMAL tasks at the queue head qualify: no dep
        materialization, no bundle accounting, FIFO preserved."""
        spec = pt.spec
        if (
            ws.state != BUSY
            or ws.prefetch_task is not None
            or not self.ready_queue
            or spec.task_type != TaskType.NORMAL_TASK
            or spec.num_returns == -1
            or spec.arg_refs
            or ws.assigned_pg is not None
        ):
            return
        sig = pt.sched_sig(spec.resources.get("TPU", 0) > 0)
        if sig is None:  # spread: placement differs per decision — no reuse
            return
        # Only pipeline when no idle worker is left to take the head task
        # directly — otherwise prefetching steals work from idle capacity
        # and SERIALIZES a small fan-out.
        idle_idx = cache.get("idle") if cache is not None else None
        if idle_idx is None or any(
            lst for kind in idle_idx.values() for lst in kind.values()
        ):
            return
        head = self.ready_queue[0]
        hspec = head.spec
        if (
            hspec.task_type != TaskType.NORMAL_TASK
            or hspec.num_returns == -1
            or hspec.arg_refs
            or hspec.task_id.hex() in self.cancelled
            or head.sched_sig(hspec.resources.get("TPU", 0) > 0) != sig
            or _task_env_key(hspec) != _task_env_key(spec)
        ):
            return
        self.ready_queue.popleft()
        task_hex = hspec.task_id.hex()
        self.running[task_hex] = (ws.worker_id, head)
        ws.prefetch_task = task_hex
        self._prefetch_ids.add(ws.worker_id)
        asyncio.ensure_future(self._dispatch_prefetch(ws, head))

    async def _dispatch_prefetch(self, ws: WorkerState, pt: PendingTask):
        spec = pt.spec
        try:
            ws.conn.post(
                {
                    "type": "execute_task",
                    "spec": spec_to_proto_bytes(spec),
                    "deps": {},
                }
            )
        except Exception:  # noqa: BLE001 — send failed: worker is dying;
            # _on_worker_death will retry the task via self.running.
            pass

    def _finish_cancelled(self, pt: PendingTask):
        self._fail_task(pt, TaskError(TaskCancelledError(), "", pt.spec.name))

    def _fail_stream(self, spec: TaskSpec, err: TaskError):
        """Terminal failure of a streaming task: one error item, then end —
        a waiting consumer must never hang."""
        self._fail_stream_hex(spec.task_id.hex(), err)

    def _fail_stream_hex(self, task_hex: str, err: TaskError):
        from .ids import TaskID

        s = self._stream(task_hex)
        if s["done"]:
            return
        idx = s["produced"]
        oid_hex = ObjectID.of(TaskID.from_hex(task_hex), idx).hex()
        self._obj(oid_hex).expected = True
        self._store_error_object(oid_hex, err)
        s["produced"] = idx + 1
        s["done"] = True
        self._wake_stream(s)

    def _fail_streams_of_actor(self, actor_hex: str, err: TaskError):
        """End every open stream owned by a dead actor's tasks. Streaming
        calls delivered over the DIRECT actor channel never pass through
        this controller as specs — a call still queued in the dead worker
        leaves only a stream entry (created lazily by the consumer's
        stream_next long-poll), and nothing else will ever end it. TaskID
        encodes the actor id, so the sweep needs no spec."""
        from .ids import TaskID

        for task_hex, s in list(self.streams.items()):
            if s["done"]:
                continue
            try:
                owner = TaskID.from_hex(task_hex).actor_id().hex()
            except Exception:  # noqa: BLE001 — malformed/foreign id
                continue
            if owner == actor_hex:
                self._fail_stream_hex(task_hex, err)

    def _fail_task(self, pt: PendingTask, err: TaskError):
        """Terminal failure for a not-yet-dispatched task: unpin args, error
        the returns, and mark a would-be actor dead."""
        spec = pt.spec
        self._unpin_args(spec)
        if spec.num_returns == -1:
            self._fail_stream(spec, err)
        if spec.task_type == TaskType.ACTOR_CREATION_TASK and spec.actor_id:
            astate = self.actors.get(spec.actor_id.hex())
            if astate is not None:
                astate.init_error = err
                self._set_actor_state(astate, "dead")
                self._drain_actor_queue(astate, err)
        for oid in spec.return_ids:
            self._store_error_object(oid.hex(), err)

    async def h_task_events(self, conn, meta, msg):
        """Batched timeline events from a worker's direct-path executions
        (reference analog: profile-event batch flushes) — keeps tracing,
        `api.timeline()`, and the running-task view complete without
        per-task control traffic."""
        events = msg.get("events", ())
        self.timeline.extend(events)
        self._trim_timeline()
        names: Dict[str, str] = {}
        for ev in events:
            kind = ev.get("event")
            task = ev.get("task")
            if kind == "task_submitted":
                names[task] = ev.get("name", "")
            elif kind == "task_dispatched":
                if len(self.direct_running) < 10_000:
                    self.direct_running[task] = {
                        "name": names.get(task, ""),
                        "worker_id": ev.get("worker", ""),
                    }
            elif kind == "task_done":
                self.direct_running.pop(task, None)
            elif kind == "task_span":
                # Consolidated per-task event (burst fast path): the task is
                # already done — only the early RUNNING pair ever inserted it.
                if ev.get("early"):
                    self.direct_running.pop(task, None)
        return None

    async def h_task_done(self, conn, meta, msg):
        task_hex = msg["task"]
        if msg.get("direct"):
            # Direct-path task on a LEASED worker: the controller's only job
            # is the object directory (results too big / ref-carrying to ride
            # the submitter socket inline) — no scheduler state to touch.
            node_id = (
                self.workers[meta["worker_id"]].node_id
                if meta.get("worker_id") in self.workers
                else HEAD_NODE
            )
            for item in msg["results"]:
                if item.get("inline") is not None:
                    self._mark_ready(
                        item["id"], inline=item["inline"],
                        size=len(item["inline"]), contains=item.get("contains"),
                    )
                else:
                    self._mark_ready(
                        item["id"], shm_name=item["name"], size=item["size"],
                        node_id=node_id, contains=item.get("contains"),
                    )
            if msg.get("stream_count") is not None:
                s = self._stream(task_hex)
                s["produced"] = max(s["produced"], msg["stream_count"])
                s["done"] = True
                self._wake_stream(s)
            if msg.get("spec") is not None:
                # Registered (arena-resident) results are reconstructible —
                # remember the creating spec like any scheduled task.
                self._remember_lineage(spec_from_proto_bytes(msg["spec"]))
            return None
        entry = self.running.pop(task_hex, None)
        if entry is not None:
            self._unpin_args(entry[1].spec)
            if entry[0].startswith("@"):  # agent-dispatched (handoff plane)
                hnode = self.nodes.get(entry[0][1:])
                if hnode is not None:
                    hnode.handoff_inflight = max(0, hnode.handoff_inflight - 1)
        ws = self.workers.get(meta["worker_id"]) if meta["worker_id"] else None
        node_id = ws.node_id if ws is not None else HEAD_NODE
        if ws is not None and ws.reclaiming_task == task_hex:
            ws.reclaiming_task = None  # reclaim lost the race — task executed
        if ws is not None and ws.state == BUSY:
            if ws.current_task == task_hex and ws.prefetch_task is not None:
                # Lease reuse: the next task is already queued on the worker —
                # keep the grant, promote, skip the idle→dispatch round trip.
                ws.current_task = ws.prefetch_task
                ws.prefetch_task = None
            else:
                ws.state = IDLE
                ws.current_task = None
                ws.prefetch_task = None
                self._grant_release(ws)
        if ws is not None and ws.actor_hex:
            astate = self.actors.get(ws.actor_hex)
            if astate is not None:
                with astate.lock:  # pump (shard loop) writes concurrently
                    ispec = astate.inflight.pop(task_hex, None)
                if ispec is not None:
                    self._unpin_args(ispec)
        for item in msg["results"]:
            if item.get("inline") is not None:
                self._mark_ready(
                    item["id"], inline=item["inline"], size=len(item["inline"]),
                    contains=item.get("contains"),
                )
            else:
                self._mark_ready(
                    item["id"], shm_name=item["name"], size=item["size"],
                    node_id=node_id, contains=item.get("contains"),
                )
        if msg.get("stream_count") is not None:
            s = self._stream(task_hex)
            s["produced"] = max(s["produced"], msg["stream_count"])
            s["done"] = True
            self._wake_stream(s)
        self._event("task_done", task=task_hex)
        self._schedule()
        return None

    async def h_actor_ready(self, conn, meta, msg):
        actor_hex = msg["actor"]
        astate = self.actors.get(actor_hex)
        task_hex = msg.get("task")
        if task_hex:
            entry = self.running.pop(task_hex, None)
            if entry is not None:
                self._unpin_args(entry[1].spec)
        if astate is None:
            return None
        if msg.get("error") is not None:
            err = serialization.unpack(msg["error"])
            astate.init_error = err
            self._set_actor_state(astate, "dead")
            self._drain_actor_queue(astate, err)
            return None
        ws = self.workers.get(meta["worker_id"])
        if ws is not None:
            astate.worker_id = ws.worker_id
        self._set_actor_state(astate, "alive")
        self._wal_append("actor_alive", actor=actor_hex,
                         worker=astate.worker_id or "")
        self._event("actor_alive", actor=actor_hex)
        return None

    def _set_actor_state(self, astate: ActorState, state: str):
        astate.state = state
        astate.wake()  # pump waits on the SHARD loop — marshal the set

    def _drain_actor_queue(self, astate: ActorState, err: TaskError):
        """Fail every queued (undelivered) call. The send queue is owned by
        the actor's shard loop — pop there, then store the error returns on
        the main loop (object directory). Calls racing this drain land on
        the shard loop in marshal order, so they are either drained here or
        see state == dead in the pump."""

        def drain():
            specs = []
            while astate.send_queue:
                spec = astate.send_queue.popleft()
                if isinstance(spec, _HandoffFence):
                    # Fail the waiter promptly; caller stays classic.
                    self._main_call_soon(
                        self._resolve_handoff_failed, spec.token
                    )
                    continue
                specs.append(spec)
            if not specs:
                return

            def store():
                for spec in specs:
                    self._unpin_args(spec)
                    if spec.num_returns == -1:
                        # Queued streaming call: end its stream with the
                        # error so the consumer's generator raises instead
                        # of long-polling forever.
                        self._fail_stream(spec, err)
                    for oid in spec.return_ids:
                        self._store_error_object(oid.hex(), err)

            self._main_call_soon(store)

        sh = astate.shard
        if sh is not None and sh.loop is not None:
            try:
                sh.loop.call_soon_threadsafe(drain)
                return
            except RuntimeError:
                pass
        drain()

    # -------------------------------------------------------------- actors
    def _register_actor(self, msg: dict) -> dict:
        """Register one actor creation (shared by the single and batched
        frames): directory entry in its shard, name claim through the
        coordination layer, creation task enqueued. One _schedule per
        BATCH happens at the caller (deferred coalescing absorbs it)."""
        spec: TaskSpec = spec_from_proto_bytes(msg["spec"])
        actor_hex = spec.actor_id.hex()
        # Dedup key: the client-minted actor id. A creation frame
        # resubmitted after a head failover (reconnect ledger) — or one
        # whose WAL record already replayed — must not register twice.
        if actor_hex in self.actors:
            return {"ok": True, "dup": True}
        bad = self._infeasible(spec.resources)
        if bad:
            astate = ActorState(actor_hex=actor_hex, spec=None, state="dead")
            err_text = (
                f"Actor {spec.name} demands {bad} but no node can fit it "
                f"(cluster total {self._cluster_totals()}) — infeasible."
            )
            astate.init_error = TaskError(RuntimeError(err_text), "", spec.name)
            self.actors[actor_hex] = astate
            astate.shard = self.actors.shard_for(actor_hex)
            self._wal_append("actor_infeasible", actor=actor_hex, error=err_text)
            return {"ok": False}
        astate = ActorState(
            actor_hex=actor_hex,
            spec=spec,
            name=msg.get("name", ""),
            namespace=msg.get("namespace", "default"),
            handle_bytes=msg.get("handle", b""),
            detached=spec.options.lifetime == "detached",
        )
        if astate.name:
            key = (astate.namespace, astate.name)
            if key in self.named_actors:
                return {"error": f"Actor name '{astate.name}' already taken"}
            self.named_actors[key] = actor_hex
        self.actors[actor_hex] = astate
        astate.shard = self.actors.shard_for(actor_hex)
        # WAL before ack (write-ahead contract): the registration + name
        # bind must be durable before any client can observe them.
        self._wal_append(
            "actor_registered",
            actor=actor_hex,
            spec=msg["spec"],
            name=astate.name,
            namespace=astate.namespace,
            handle=msg.get("handle", b""),
            detached=astate.detached,
        )
        self._pin_args(spec)
        pt = PendingTask(spec=spec, retries_left=0)
        self._event("actor_created", actor=actor_hex, name=astate.name)
        self._enqueue(pt)
        return {"ok": True}

    async def h_create_actor(self, conn, meta, msg):
        out = self._register_actor(msg)
        self._schedule()
        return out

    async def h_create_actor_batch(self, conn, meta, msg):
        """Coalesced creation frames from one client (cluster_backend
        batches anonymous creations): N directory registrations, ONE
        scheduling request — a 2,000-actor wave is a handful of passes
        instead of 2,000 (reference analog: the GCS's batched actor
        registration RPCs feeding one scheduling round)."""
        for item in msg["items"]:
            self._register_actor(item)
        self._schedule()
        return None

    async def _send_actor_task(self, astate: ActorState, spec: TaskSpec):
        def fail(err: TaskError):
            with astate.lock:
                astate.inflight.pop(spec.task_id.hex(), None)
            self._unpin_args(spec)
            for oid in spec.return_ids:
                self._store_error_object(oid.hex(), err)

        ws = self.workers.get(astate.worker_id)
        if ws is None or ws.conn is None or ws.state == DEAD:
            fail(TaskError(ActorDiedError(), "", spec.name))
            return
        try:
            await asyncio.gather(
                *(self._ensure_local(ws.node_id, oid.hex()) for oid in spec.arg_refs)
            )
        except Exception as e:  # noqa: BLE001
            fail(TaskError(RuntimeError(f"dependency transfer failed: {e}"), "", spec.name))
            return
        try:
            ws.conn.post(
                {
                    "type": "execute_actor_task",
                    "spec": spec_to_proto_bytes(spec),
                    "deps": self._deps_payload_safe(spec, ws.node_id),
                }
            )
        except ConnectionError:
            fail(TaskError(ActorDiedError(), "", spec.name))

    def _deps_payload_safe(self, spec: TaskSpec, node_id: str) -> dict:
        locs = {}
        for oid in spec.arg_refs:
            h = oid.hex()
            obj = self.objects.get(h)
            locs[h] = (
                self._location_payload(obj, node_id)
                if obj and obj.status == "ready"
                else {"status": "pending"}
            )
        return locs

    async def h_submit_actor_task(self, conn, meta, msg):
        spec: TaskSpec = spec_from_proto_bytes(msg["spec"])
        actor_hex = spec.actor_id.hex()
        astate = self.actors.get(actor_hex)
        if astate is None or astate.state == "dead":
            err = astate.init_error if astate else None
            err = err or TaskError(ActorDiedError(), "", spec.name)
            if spec.num_returns == -1:
                # Streaming call to a dead actor: return_ids is EMPTY — only
                # ending the stream itself stops the consumer's long-poll
                # (observed: next() waiting out the full stream timeout).
                self._fail_stream(spec, err)
            for oid in spec.return_ids:
                self._store_error_object(oid.hex(), err)
            return {"ok": False}
        self._pin_args(spec)
        self._expect_returns(spec)
        self._shard_enqueue(astate, spec)
        return {"ok": True}

    # -------------------------------------------- shard delivery plane
    # The actor send queue + pump live on the actor's SHARD loop
    # (control_shards.py): the main loop marshals appends/drains there and
    # the pump marshals object-directory work back. FIFO order per
    # submitting thread is preserved by call_soon_threadsafe.
    def _main_call_soon(self, fn, *args):
        """Run fn on the main (scheduler/object-directory) loop; inline when
        already there — shard-loop callers get a deferred, ordered call."""
        loop = getattr(self, "_main_loop", None)
        if loop is None:
            fn(*args)
            return
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if running is loop:
            fn(*args)
            return
        try:
            loop.call_soon_threadsafe(fn, *args)
        except RuntimeError:
            pass  # main loop closed (shutdown)

    async def _run_on_main(self, coro):
        """Await a coroutine on the main loop from a shard loop (ordered
        delivery steps that need scheduler/object state)."""
        loop = asyncio.get_running_loop()
        main = getattr(self, "_main_loop", None)
        if main is None or loop is main:
            return await coro
        return await asyncio.wrap_future(
            asyncio.run_coroutine_threadsafe(coro, main)
        )

    def _shard_enqueue(self, astate: ActorState, item):
        """Append to the actor's ordered send queue and ensure its pump
        runs — both on the owning shard's loop (single-writer)."""

        def run():
            astate.send_queue.append(item)
            if not astate.pump_active:
                astate.pump_active = True
                asyncio.get_running_loop().create_task(self._pump_actor(astate))

        sh = astate.shard
        if sh is not None and sh.loop is not None:
            try:
                sh.loop.call_soon_threadsafe(run)
                return
            except RuntimeError:
                pass  # shard loop stopped (shutdown) — fall through
        # No shard loop (unit tests poking controller state directly).
        astate.send_queue.append(item)
        if not astate.pump_active:
            astate.pump_active = True
            asyncio.ensure_future(self._pump_actor(astate))

    async def _shard_wait_ready(self, hex_id: str):
        """Shard-side wait for an object's readiness. Registration happens
        ON the main loop (the object directory's owner — a racy check-then
        -append from this thread could miss the wake between _mark_ready's
        event sweep and clear)."""
        loop = asyncio.get_running_loop()
        main = getattr(self, "_main_loop", None)
        if main is None or loop is main:
            obj = self._obj(hex_id)
            while obj.status != "ready":
                ev = asyncio.Event()
                obj.events.append(ev)
                await ev.wait()
            return
        from .control_shards import CrossLoopEvent

        while True:
            sev = asyncio.Event()

            def reg():
                obj = self._obj(hex_id)
                if obj.status == "ready":
                    try:
                        loop.call_soon_threadsafe(sev.set)
                    except RuntimeError:
                        pass
                else:
                    obj.events.append(CrossLoopEvent(loop, sev))

            self._main_call_soon(reg)
            await sev.wait()
            obj = self.objects.get(hex_id)
            if obj is not None and obj.status == "ready":
                return

    def _fail_actor_call(self, spec: TaskSpec, err: Optional[TaskError]):
        """Store error returns for an undeliverable actor call — on the
        main loop (object directory owner); callable from shard loops."""
        err = err or TaskError(ActorDiedError(), "", spec.name)

        def run():
            self._unpin_args(spec)
            if spec.num_returns == -1:
                self._fail_stream(spec, err)
            for oid in spec.return_ids:
                self._store_error_object(oid.hex(), err)

        self._main_call_soon(run)

    async def _pump_actor(self, astate: ActorState):
        """Deliver this actor's calls strictly in submission order — runs on
        the actor's SHARD loop. Argless calls to a live actor (the
        steady-state hot path) are delivered entirely shard-side via the
        thread-safe conn.post; calls needing the object directory
        (arg deps, error returns) marshal through the main loop."""
        try:
            while astate.send_queue:
                spec = astate.send_queue[0]
                for oid in spec.arg_refs:
                    await self._shard_wait_ready(oid.hex())
                while astate.state in ("pending", "restarting"):
                    astate.state_event.clear()
                    await astate.state_event.wait()
                if not astate.send_queue or astate.send_queue[0] is not spec:
                    continue  # queue drained by a death path while we waited
                astate.send_queue.popleft()
                if isinstance(spec, _HandoffFence):
                    ws = self.workers.get(astate.worker_id)
                    if astate.state == "alive" and ws is not None and ws.conn is not None:
                        try:
                            ws.conn.post(
                                {"type": "actor_handoff", "token": spec.token}
                            )
                        except Exception:  # noqa: BLE001 — waiter times out
                            pass
                    else:
                        # Dead/unreachable: answer the handoff waiter NOW —
                        # the caller falls back to classic (and its buffered
                        # calls fail fast) instead of waiting out the 30s
                        # handoff timeout against a dead actor.
                        self._main_call_soon(
                            self._resolve_handoff_failed, spec.token
                        )
                    continue
                if astate.state == "dead":
                    self._fail_actor_call(spec, astate.init_error)
                    continue
                task_hex = spec.task_id.hex()
                with astate.lock:
                    astate.inflight[task_hex] = spec
                if not spec.arg_refs:
                    ws = self.workers.get(astate.worker_id)
                    if ws is None or ws.conn is None or ws.state == DEAD:
                        with astate.lock:
                            astate.inflight.pop(task_hex, None)
                        self._fail_actor_call(
                            spec, TaskError(ActorDiedError(), "", spec.name)
                        )
                        continue
                    try:
                        ws.conn.post(
                            {
                                "type": "execute_actor_task",
                                "spec": spec_to_proto_bytes(spec),
                                "deps": {},
                            }
                        )
                    except ConnectionError:
                        with astate.lock:
                            astate.inflight.pop(task_hex, None)
                        self._fail_actor_call(
                            spec, TaskError(ActorDiedError(), "", spec.name)
                        )
                    continue
                await self._run_on_main(self._send_actor_task(astate, spec))
        finally:
            if astate.send_queue and not self._shutdown_event.is_set():
                # A racer appended between our last check and this exit
                # (same loop, so this check-and-restart is atomic). The
                # shutdown guard keeps a closing main loop from turning a
                # failing pump into a restart spin.
                asyncio.get_running_loop().create_task(self._pump_actor(astate))
            else:
                astate.pump_active = False

    async def h_kill_actor(self, conn, meta, msg):
        actor_hex = msg["actor"]
        no_restart = msg.get("no_restart", True)
        astate = self.actors.get(actor_hex)
        if astate is None:
            return {"ok": False}
        self._set_actor_state(astate, "dead")
        if no_restart:
            astate.spec = None
        self._wal_append("actor_killed", actor=actor_hex, no_restart=no_restart)
        err = TaskError(ActorDiedError("Actor was killed."), "", "actor task")
        self._drain_actor_queue(astate, err)
        # Inflight (already-delivered) calls can never complete either — the
        # worker is being terminated. Fail them NOW: a delivered streaming
        # call otherwise leaves its consumer long-polling out the full
        # stream timeout (observed: 300s for a one-line test). Results that
        # raced ahead and completed are left alone (ready check below).
        with astate.lock:  # pump (shard loop) writes concurrently
            inflight = list(astate.inflight.values())
            astate.inflight.clear()
        for ispec in inflight:
            self._unpin_args(ispec)
            if ispec.num_returns == -1:
                self._fail_stream(ispec, err)
            for oid in ispec.return_ids:
                if self._obj(oid.hex()).status != "ready":
                    self._store_error_object(oid.hex(), err)
        for key, ah in list(self.named_actors.items()):
            if ah == actor_hex:
                del self.named_actors[key]
        # Streams of direct-plane calls queued in the dying worker have no
        # controller-side spec to drain — end them by owner id.
        self._fail_streams_of_actor(actor_hex, err)
        ws = self.workers.get(astate.worker_id)
        if ws is not None:
            self._terminate_worker(ws)
        return {"ok": True}

    def _terminate_worker(self, ws: WorkerState):
        """SIGTERM a worker wherever it lives (head: direct child; remote:
        via its node agent, since a busy worker won't read an exit message)."""
        proc = self._worker_procs.get(ws.worker_id)
        if proc is not None:
            if proc.poll() is None:
                proc.terminate()
            return
        node = self.nodes.get(ws.node_id)
        if node is not None and node.conn is not None and node.alive:
            try:
                node.conn.post(
                    {"type": "kill_worker", "worker_id": ws.worker_id}
                )
            except ConnectionError:
                pass  # node dying; its workers die with it

    async def h_get_named_actor(self, conn, meta, msg):
        key = (msg.get("namespace", "default"), msg["name"])
        actor_hex = self.named_actors.get(key)
        if actor_hex is None:
            return {"handle": None}
        astate = self.actors.get(actor_hex)
        return {"handle": astate.handle_bytes if astate else None}

    # -------------------------------------------------------- worker death
    async def _on_worker_death(self, worker_id: str):
        ws = self.workers.get(worker_id)
        if ws is None:
            return
        prev_state = ws.state
        ws.state = DEAD
        self._leased_ids.discard(worker_id)
        ws.leased_to = None  # holder sees the direct conn close and recovers
        if ws.assigned:
            if not ws.blocked:
                self._grant_release(ws)
            else:  # capacity already released at block time
                ws.assigned = {}
                ws.assigned_pg = None
        self._worker_procs.pop(worker_id, None)
        if prev_state == BUSY and ws.current_task:
            dead_tasks = [(ws.current_task, True)]
            if ws.prefetch_task is not None:
                dead_tasks.append((ws.prefetch_task, False))
                ws.prefetch_task = None
            for task_hex, started in dead_tasks:
                entry = self.running.pop(task_hex, None)
                if entry is None:
                    continue
                _, pt = entry
                if task_hex in self.cancelled:
                    self._finish_cancelled(pt)
                elif not started:
                    # Prefetched-but-never-executed: plain requeue, no retry
                    # consumed (it would have still been in ready_queue
                    # without prefetch).
                    pt.pinned_node = None
                    self._enqueue(pt)
                else:
                    cause = (
                        f"Worker {worker_id} was killed by the memory "
                        f"monitor (node out of memory) while executing task"
                        if ws.oom_killed
                        else f"Worker {worker_id} died executing task"
                    )
                    self._retry_or_fail(pt, task_hex, cause)
        if prev_state == ACTOR and ws.actor_hex:
            await self._on_actor_worker_death(ws.actor_hex)
        # Keep the pool topped up. Queue-emptiness first: any() short-circuits
        # on the first idle worker, so a 5,000-actor kill wave doesn't pay a
        # full worker-table scan per death.
        if (self.ready_queue or self.waiting_tasks) and not any(
            w.state == IDLE for w in self.workers.values()
        ):
            self._spawn_worker()
        self._schedule()

    async def _on_actor_worker_death(self, actor_hex: str):
        astate = self.actors.get(actor_hex)
        if astate is None or astate.state == "dead":
            return
        spec = astate.spec
        max_restarts = spec.options.max_restarts if spec else 0
        # Calls delivered to the dead worker can never complete — fail exactly
        # those (tracked in `inflight`; queued-but-unsent calls are unaffected).
        from .exceptions import ActorUnavailableError

        if spec is not None and (max_restarts == -1 or astate.restarts_used < max_restarts):
            astate.restarts_used += 1
            self._set_actor_state(astate, "restarting")
            self._wal_append("actor_restarting", actor=actor_hex,
                             restarts_used=astate.restarts_used)
            self._event("actor_restarting", actor=actor_hex)
            err = TaskError(
                ActorUnavailableError(f"actor {actor_hex[:12]} restarting"), "", "actor task"
            )
            with astate.lock:  # pump (shard loop) writes concurrently
                inflight = list(astate.inflight.values())
                astate.inflight.clear()
            for ispec in inflight:
                self._unpin_args(ispec)
                if ispec.num_returns == -1:
                    self._fail_stream(ispec, err)  # streaming method call
                for oid in ispec.return_ids:
                    if self._obj(oid.hex()).status != "ready":
                        self._store_error_object(oid.hex(), err)
            self._pin_args(spec)  # restart creation re-reads its args
            pt = PendingTask(spec=spec, retries_left=0)
            self._enqueue(pt)
            self._schedule()
        else:
            self._set_actor_state(astate, "dead")
            self._wal_append("actor_death", actor=actor_hex)
            self._event("actor_death", actor=actor_hex,
                        restarts_used=astate.restarts_used)
            err = TaskError(ActorDiedError(), "", f"actor {actor_hex[:12]}")
            self._drain_actor_queue(astate, err)
            self._fail_streams_of_actor(actor_hex, err)
            with astate.lock:  # pump (shard loop) writes concurrently
                inflight = list(astate.inflight.values())
                astate.inflight.clear()
            for ispec in inflight:
                self._unpin_args(ispec)
                if ispec.num_returns == -1:
                    self._fail_stream(ispec, err)  # streaming method call
                for oid in ispec.return_ids:
                    if self._obj(oid.hex()).status != "ready":
                        self._store_error_object(oid.hex(), err)

    # ---------------------------------------------------------- node death
    async def _health_check_loop(self):
        """Active liveness probing of node agents (reference:
        `GcsHealthCheckManager`, `gcs_health_check_manager.h:39`): a wedged
        agent whose TCP connection is still up would otherwise hold its
        node 'alive' forever — connection-close detection only covers
        process death."""
        period = rt_config.get("health_check_period_s")
        timeout = rt_config.get("health_check_timeout_s")
        threshold = rt_config.get("health_check_failures")
        misses: Dict[str, int] = {}
        async def probe(node: NodeState):
            try:
                resp = await node.conn.request({"type": "ping"}, timeout=timeout)
                ok = bool((resp or {}).get("ok"))
                if ok and resp.get("sys"):
                    node.sys_metrics = resp["sys"]
                if ok:
                    node.agent_alive_workers = set(
                        resp.get("spawned_alive") or ()
                    )
            except Exception:  # noqa: BLE001
                ok = False
            if ok:
                misses.pop(node.node_id, None)
                return
            misses[node.node_id] = misses.get(node.node_id, 0) + 1
            if misses[node.node_id] >= threshold:
                self._event("node_health_check_failed", node=node.node_id)
                misses.pop(node.node_id, None)
                try:
                    node.conn.close()
                except Exception:  # noqa: BLE001
                    pass
                await self._on_node_death(node.node_id)

        from ..util.system_metrics import SystemMetricsSampler

        head_sampler = SystemMetricsSampler()
        while not self._shutdown_event.is_set():
            await asyncio.sleep(period)
            # Concurrent probes: one wedged node must not delay (or inflate
            # the detection latency of) every other node's probe.
            targets = [
                n for n in self.nodes.values() if n.alive and n.conn is not None
            ]
            if targets:
                await asyncio.gather(*(probe(n) for n in targets))
            try:
                self.head.sys_metrics = head_sampler.sample()
            except Exception:  # noqa: BLE001
                pass
            self._expire_spawn_ledger()
            if self.ready_queue and self._iso_booting:
                # Scheduling is event-driven; an isolated spawn that died
                # before registering produces NO event. This tick is what
                # advances the dead-attempt counter (_spawn_isolated) so a
                # broken env converges to RuntimeEnvSetupError instead of
                # hanging its tasks forever.
                self._schedule()

    def _expire_spawn_ledger(self):
        """Spawns that never registered (interpreter died / wedged) must
        give their boot budget back — a leaked `spawning` count would
        eventually starve the global worker_boot_concurrency cap."""
        now = time.monotonic()
        keep = []
        expired = False
        for entry in self._spawn_ledger:
            node_id, t0, tpu = entry
            if now - t0 < 180.0:
                keep.append(entry)
                continue
            expired = True
            node = self.nodes.get(node_id)
            if node is not None:
                node.spawning = max(0, node.spawning - 1)
                if tpu:
                    node.spawning_tpu = max(0, node.spawning_tpu - 1)
            self._event("spawn_expired", node=node_id)
        self._spawn_ledger = keep
        if expired:
            # Freed boot budget must re-fire deferred spawn demand — with a
            # blocked client and no other events, nothing else schedules.
            self._schedule()

    async def _on_node_death(self, node_id: str):
        """A node agent's connection dropped (reference analog: GCS node
        death pubsub after `GcsHealthCheckManager` misses)."""
        node = self.nodes.get(node_id)
        if node is None or not node.alive:
            return
        node.alive = False
        self._fetch_conns.pop(node_id, None)
        self._event("node_died", node=node_id)
        # Tasks handed to its LocalDispatcher die with it — same retry
        # policy as worker death.
        marker = f"@{node_id}"
        for task_hex, (wid, pt) in list(self.running.items()):
            if wid == marker:
                self.running.pop(task_hex, None)
                self._retry_or_fail(
                    pt, task_hex, f"Node {node_id} died with task queued"
                )
        node.handoff_inflight = 0
        # Its workers are dying with it (PDEATHSIG); process them now so
        # running tasks retry immediately rather than on socket timeout.
        for ws in list(self.workers.values()):
            if ws.node_id == node_id and ws.state != DEAD:
                await self._on_worker_death(ws.worker_id)
        # Objects whose only copy lived there are lost (until lineage
        # reconstruction re-executes their creators).
        for hex_id, obj in self.objects.items():
            obj.locations.pop(node_id, None)
            if obj.spilled_path is not None and obj.spilled_node == node_id:
                obj.spilled_path = None
        # Re-place ONLY the bundles that sat on the dead node (reference
        # analog: `GcsPlacementGroupManager` rescheduling on node removal).
        # Bundles on surviving nodes keep their reservations untouched —
        # releasing them would double-book capacity still used by running
        # gang members.
        for pg_hex, pg in self.pgs.items():
            dead_idx = [
                i for i, nid in enumerate(pg["bundle_nodes"]) if nid == node_id
            ]
            if not dead_idx:
                continue
            dead_bundles = [pg["bundles"][i] for i in dead_idx]
            surviving = {
                nid for nid in pg["bundle_nodes"] if nid and nid != node_id
            }
            placement = self._place_bundles(
                dead_bundles, pg["strategy"], occupied=surviving
            )
            if placement is None:
                pg["ready"] = False  # blocks new PG dispatch; grants continue
                for i in dead_idx:
                    pg["bundle_nodes"][i] = None
                self._event("pg_infeasible_after_node_death", pg=pg_hex)
            else:
                for i, nid in zip(dead_idx, placement):
                    self._acquire(self.nodes[nid], pg["bundles"][i])
                    pg["bundle_nodes"][i] = nid
                    pg["bundle_avail"][i] = dict(pg["bundles"][i])
                self._event("pg_rescheduled", pg=pg_hex, bundles=dead_idx)
        self._schedule()

    # ------------------------------------------------------------ blocking
    # ------------------------------------------------------ memory monitor
    # Reference analog: `memory_monitor.h:52` sampling + the raylet's
    # worker-killing policy (`worker_killing_policy_group_by_owner.cc`).
    # Agents report candidates; the controller picks with global knowledge.
    async def h_memory_pressure(self, conn, meta, msg):
        node_id = msg.get("node_id", HEAD_NODE)
        victim = self._pick_oom_victim(node_id, msg.get("candidates") or [])
        if victim is None:
            return None
        victim.oom_killed = True
        self._event(
            "oom_kill", worker=victim.worker_id, node=node_id,
            used=msg.get("used"), limit=msg.get("limit"),
        )
        node = self.nodes.get(node_id)
        if node is not None and node.conn is not None:
            await node.conn.send(
                {"type": "kill_worker", "worker_id": victim.worker_id}
            )
        else:
            self._terminate_worker(victim)
        return None

    def _pick_oom_victim(self, node_id: str, candidates) -> Optional["WorkerState"]:
        """Largest-RSS TASK worker first; an actor host only when no task
        worker remains (the reference's policy spares actors the same way —
        killing one loses state, not just one retryable task)."""
        task_pick = actor_pick = None
        for worker_id, _rss in candidates:  # already sorted largest-first
            ws = self.workers.get(worker_id)
            if ws is None or ws.state == DEAD or ws.node_id != node_id:
                continue
            if ws.state == ACTOR:
                actor_pick = actor_pick or ws
            else:
                task_pick = task_pick or ws
                break
        return task_pick or actor_pick

    async def _head_memory_monitor_loop(self):
        """The head node has no agent — the controller samples its own
        spawned workers with the same policy."""
        from ..util.memory_monitor import MemoryPressureSampler

        interval = rt_config.get("memory_monitor_interval_s")
        if not interval:
            return
        sampler = MemoryPressureSampler(
            rt_config.get("memory_limit_bytes"),
            rt_config.get("memory_usage_threshold"),
        )
        while not self._shutdown_event.is_set():
            await asyncio.sleep(interval)
            try:
                over = sampler.over_threshold()
                if over is None:
                    continue
                pids = {
                    wid: p.pid for wid, p in list(self._worker_procs.items())
                    if p.poll() is None
                }
                if not pids:
                    continue
                await self.h_memory_pressure(
                    None, {},
                    {"node_id": HEAD_NODE,
                     "candidates": sampler.candidates(pids), **over},
                )
                await asyncio.sleep(interval)
            except Exception:  # noqa: BLE001
                traceback.print_exc()

    async def h_worker_blocked(self, conn, meta, msg):
        ws = self.workers.get(msg["worker_id"])
        if ws is not None and not ws.blocked:
            ws.blocked = True
            self._grant_release_keep(ws)
            self._schedule()
        return None

    async def h_worker_unblocked(self, conn, meta, msg):
        ws = self.workers.get(msg["worker_id"])
        if ws is not None and ws.blocked:
            ws.blocked = False
            self._grant_reacquire(ws)
        return None

    # ------------------------------------------------------------- cancel
    async def h_cancel(self, conn, meta, msg):
        task_hex = msg["task"]
        self.cancelled.add(task_hex)
        entry = self.running.get(task_hex)
        if entry is not None:
            worker_id, pt = entry
            if worker_id.startswith("@"):
                # Queued/running at a node agent: drop there; force also
                # kills the executing worker (the agent knows which one —
                # h_agent_task_cancelled / h_agent_task_lost finish the
                # bookkeeping).
                node = self.nodes.get(worker_id[1:])
                if node is not None and node.conn is not None and node.alive:
                    node.conn.post({"type": "cancel_task", "task": task_hex,
                                    "force": bool(msg.get("force"))})
                return {"ok": True}
            ws = self.workers.get(worker_id)
            if ws is not None and ws.prefetch_task == task_hex:
                # Prefetched but not yet executing: drop it on the worker —
                # force-killing would take down the UNRELATED current task.
                ws.prefetch_task = None
                self.running.pop(task_hex, None)
                try:
                    await ws.conn.send({"type": "drop_task", "task": task_hex})
                except Exception:  # noqa: BLE001
                    pass
                self._finish_cancelled(pt)
                self._schedule()
                return {"ok": True}
            if msg.get("force") and ws is not None:
                self._terminate_worker(ws)
        # Pending-in-queue tasks are culled in _schedule.
        pt = self.waiting_tasks.pop(task_hex, None)
        if pt is not None:
            self._finish_cancelled(pt)
        self._schedule()
        return {"ok": True}

    # ---------------------------------------------------- placement groups
    async def h_create_pg(self, conn, meta, msg):
        """Per-bundle placement onto nodes (reference analog:
        `BundleSchedulingPolicy` PACK/SPREAD/STRICT_* in
        `bundle_scheduling_policy.cc`). Reserves each bundle against a
        concrete node; bundle->node mapping drives bundle_index scheduling."""
        bundles: List[Dict[str, float]] = msg["bundles"]
        strategy = msg["strategy"]
        placement = self._place_bundles(bundles, strategy)
        feasible = placement is not None
        if feasible:
            for b, nid in zip(bundles, placement):
                self._acquire(self.nodes[nid], b)
        self.pgs[msg["id"]] = {
            "bundles": bundles,
            "strategy": strategy,
            "name": msg.get("name", ""),
            "ready": feasible,
            "bundle_nodes": placement or [],
            # Unconsumed capacity per bundle: PG tasks draw from here, not
            # from general node availability (it is already reserved).
            "bundle_avail": [dict(b) for b in bundles],
        }
        self._wal_append(
            "pg_created", pg=msg["id"], bundles=bundles, strategy=strategy,
            name=msg.get("name", ""), ready=feasible,
            bundle_nodes=placement or [],
        )
        return {"ok": feasible}

    def _place_bundles(
        self,
        bundles: List[Dict[str, float]],
        strategy: str,
        occupied: Optional[Set[str]] = None,
    ) -> Optional[List[str]]:
        """Map bundles to nodes per the PG strategy; None if infeasible.
        Works against a scratch copy of availability so partial placements
        never leak reservations. `occupied` seeds STRICT_SPREAD's used-node
        set (partial re-placement after a node death)."""
        alive = [n for n in self.nodes.values() if n.alive]
        avail = {n.node_id: dict(n.available) for n in alive}

        def fits(nid: str, b: Dict[str, float]) -> bool:
            a = avail[nid]
            return all(a.get(k, 0.0) + 1e-9 >= v for k, v in b.items())

        def take(nid: str, b: Dict[str, float]):
            a = avail[nid]
            for k, v in b.items():
                a[k] = a.get(k, 0.0) - v

        placement: List[str] = []
        if strategy in ("PACK", "STRICT_PACK"):
            order = sorted(avail, key=lambda nid: (nid != HEAD_NODE, nid))
            for b in bundles:
                chosen = None
                for nid in (placement[-1:] if strategy == "STRICT_PACK" and placement else []) + order:
                    if fits(nid, b):
                        chosen = nid
                        break
                if chosen is None:
                    return None
                if strategy == "STRICT_PACK" and placement and chosen != placement[0]:
                    return None
                take(chosen, b)
                placement.append(chosen)
            if strategy == "STRICT_PACK" and len(set(placement)) > 1:
                return None
            return placement
        # SPREAD / STRICT_SPREAD: round-robin across distinct nodes.
        used: Set[str] = set(occupied or ())
        for b in bundles:
            fresh = [nid for nid in sorted(avail) if nid not in used and fits(nid, b)]
            any_fit = [nid for nid in sorted(avail) if fits(nid, b)]
            if strategy == "STRICT_SPREAD":
                if not fresh:
                    return None  # needs a distinct node per bundle
                chosen = fresh[0]
            else:
                chosen = fresh[0] if fresh else (any_fit[0] if any_fit else None)
                if chosen is None:
                    return None
            take(chosen, b)
            placement.append(chosen)
            used.add(chosen)
        return placement

    async def h_pg_ready(self, conn, meta, msg):
        pg = self.pgs.get(msg["id"])
        return {"ready": bool(pg and pg["ready"])}

    async def h_pg_table(self, conn, meta, msg):
        pg = self.pgs.get(msg["id"])
        if pg is None:
            return {"pg": None}
        return {"pg": {k: pg[k] for k in ("bundles", "strategy", "name", "ready", "bundle_nodes")}}

    async def h_remove_pg(self, conn, meta, msg):
        pg = self.pgs.pop(msg["id"], None)
        if pg is not None:
            self._wal_append("pg_removed", pg=msg["id"])
        if pg and pg["bundle_nodes"]:
            # Release every still-placed bundle — including those of a PG
            # demoted to not-ready after a node death (its surviving bundles
            # keep reservations until removal).
            for b, nid in zip(pg["bundles"], pg["bundle_nodes"]):
                node = self.nodes.get(nid) if nid else None
                if node is not None and node.alive:
                    self._release(node, b)
            self._schedule()
        return {"ok": True}

    # ------------------------------------------------- streaming generators
    # Reference analog: `returns_dynamic` / ObjectRefGenerator
    # (`_raylet.pyx:272`) — a task's yields become objects as produced.
    def _stream(self, task_hex: str) -> dict:
        s = self.streams.get(task_hex)
        if s is None:
            s = self.streams[task_hex] = {"produced": 0, "done": False, "events": []}
        return s

    def _wake_stream(self, s: dict):
        for ev in s["events"]:
            ev.set()
        s["events"].clear()

    async def h_stream_item(self, conn, meta, msg):
        ws = self.workers.get(meta["worker_id"]) if meta.get("worker_id") else None
        node_id = ws.node_id if ws is not None else HEAD_NODE
        item = msg["item"]
        hex_id = item["id"]
        self._obj(hex_id).expected = True
        if item.get("inline") is not None:
            self._mark_ready(hex_id, inline=item["inline"], size=len(item["inline"]),
                             contains=item.get("contains"))
        else:
            self._mark_ready(hex_id, shm_name=item["name"], size=item["size"],
                             node_id=node_id, contains=item.get("contains"))
        s = self._stream(msg["task"])
        s["produced"] = max(s["produced"], msg["index"] + 1)
        self._wake_stream(s)
        return None

    async def h_stream_release(self, conn, meta, msg):
        """Consumer abandoned/finished the stream: indices it never claimed
        become GC-eligible (they were never announced as held), and the
        stream bookkeeping goes once the producer is done."""
        task_hex = msg["task"]
        s = self.streams.get(task_hex)
        if s is None:
            return None
        task_id = None
        for i in range(msg.get("from_index", 0), s["produced"]):
            if task_id is None:
                from .ids import TaskID

                task_id = TaskID.from_hex(task_hex)
            hex_id = ObjectID.of(task_id, i).hex()
            obj = self.objects.get(hex_id)
            if obj is not None:
                obj.ever_held = True  # unclaimed → GC-eligible
                self._maybe_gc(hex_id)
        if s["done"]:
            self.streams.pop(task_hex, None)
        return None

    async def h_stream_next(self, conn, meta, msg):
        """Long-poll for the consumer: next index ready | end | timeout."""
        task_hex, index = msg["task"], msg["index"]
        timeout = msg.get("timeout")
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            s = self._stream(task_hex)
            if index < s["produced"]:
                return {"status": "ready"}
            if s["done"]:
                return {"status": "end"}
            # Dead-owner check: a streaming call queued in a dead actor's
            # worker (direct plane) produces no items and no done — only
            # the owning actor's state says so. Without this, the first
            # poll to race the death sweep long-polls out its full timeout.
            from .ids import TaskID

            try:
                owner = TaskID.from_hex(task_hex).actor_id().hex()
            except Exception:  # noqa: BLE001
                owner = None
            astate = self.actors.get(owner) if owner else None
            if astate is not None and astate.state == "dead":
                err = astate.init_error or TaskError(
                    ActorDiedError(), "", f"actor {owner[:12]}"
                )
                self._fail_stream_hex(task_hex, err)
                continue  # loop re-reads: first poll gets the error item
            ev = asyncio.Event()
            s["events"].append(ev)
            try:
                if deadline is None:
                    await ev.wait()
                else:
                    await asyncio.wait_for(
                        ev.wait(), max(0.0, deadline - time.monotonic())
                    )
            except asyncio.TimeoutError:
                return {"status": "timeout"}
            finally:
                if ev in s["events"]:
                    s["events"].remove(ev)

    # ---------------------------------------------------------------- jobs
    # Reference analog: `dashboard/modules/job/job_manager.py` — the job
    # runs as a supervised DRIVER subprocess on the head node; the client
    # (`JobSubmissionClient`) polls status and streams logs.
    async def h_submit_job(self, conn, meta, msg):
        import shlex

        job_id = f"job-{next(self._conn_counter):04d}-{os.getpid() % 10000}"
        entrypoint = msg["entrypoint"]
        runtime_env = msg.get("runtime_env") or {}
        env = dict(os.environ)
        env.update({k: str(v) for k, v in (runtime_env.get("env_vars") or {}).items()})
        env["RAY_TPU_ADDRESS"] = f"{self.node_ip}:{self.port}"
        env["RAY_TPU_JOB_ID"] = job_id
        pkg_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        env["PYTHONUNBUFFERED"] = "1"
        cwd = runtime_env.get("working_dir") or pkg_root
        log_path = os.path.join(self.session_dir, f"{job_id}.log")
        try:
            proc = subprocess.Popen(
                shlex.split(entrypoint),
                env=env,
                stdout=open(log_path, "ab"),
                stderr=subprocess.STDOUT,
                cwd=cwd,
            )
        except OSError as e:
            return {"job_id": job_id, "status": "FAILED", "error": repr(e)}
        self.jobs[job_id] = {
            "proc": proc,
            "pid": proc.pid,
            "entrypoint": entrypoint,
            "status": "RUNNING",
            "log_path": log_path,
            "start_time": time.time(),
            "end_time": None,
        }
        self._event("job_submitted", job=job_id, entrypoint=entrypoint)
        return {"job_id": job_id, "status": "RUNNING"}

    def _job_view(self, job_id: str, j: dict) -> dict:
        proc = j.get("proc")
        if j["status"] == "RUNNING":
            if proc is not None:
                if proc.poll() is not None:
                    j["status"] = "SUCCEEDED" if proc.returncode == 0 else "FAILED"
                    j["end_time"] = time.time()
            elif not os.path.exists(f"/proc/{j.get('pid', 0)}"):
                # Re-adopted after controller restart: the job isn't our
                # child, so its exit code is unknowable.
                j["status"] = "UNKNOWN"
                j["end_time"] = time.time()
        return {
            "job_id": job_id,
            "status": j["status"],
            "entrypoint": j["entrypoint"],
            "returncode": proc.poll() if proc is not None else None,
            "start_time": j["start_time"],
            "end_time": j["end_time"],
        }

    async def h_job_status(self, conn, meta, msg):
        j = self.jobs.get(msg["job_id"])
        if j is None:
            return {"error": f"no such job {msg['job_id']}"}
        return self._job_view(msg["job_id"], j)

    async def h_list_jobs(self, conn, meta, msg):
        return {"jobs": [self._job_view(jid, j) for jid, j in self.jobs.items()]}

    async def h_job_logs(self, conn, meta, msg):
        j = self.jobs.get(msg["job_id"])
        if j is None:
            return {"error": f"no such job {msg['job_id']}"}
        try:
            with open(j["log_path"], "rb") as f:
                f.seek(msg.get("offset", 0))
                data = f.read(1 << 20)
            return {"data": data.decode(errors="replace"),
                    "offset": msg.get("offset", 0) + len(data)}
        except OSError:
            return {"data": "", "offset": 0}

    async def h_stop_job(self, conn, meta, msg):
        j = self.jobs.get(msg["job_id"])
        if j is None:
            return {"ok": False}
        proc = j.get("proc")
        try:
            if proc is not None and proc.poll() is None:
                proc.terminate()
                j["status"] = "STOPPED"
                j["end_time"] = time.time()
            elif proc is None and os.path.exists(f"/proc/{j.get('pid', 0)}"):
                os.kill(j["pid"], 15)  # re-adopted job (not our child)
                j["status"] = "STOPPED"
                j["end_time"] = time.time()
        except OSError:
            pass
        self._event("job_stopped", job=msg["job_id"])
        return {"ok": True}

    # ------------------------------------------------------ fault injection
    async def h_kill_worker(self, conn, meta, msg):
        """Chaos hook (reference: `WorkerKillerActor`, `test_utils.py:1527`)."""
        ws = self.workers.get(msg["worker_id"])
        if ws is None or ws.state == DEAD:
            return {"ok": False}
        self._terminate_worker(ws)
        self._event("chaos_worker_killed", worker=ws.worker_id)
        return {"ok": True}

    async def h_kill_node(self, conn, meta, msg):
        """Chaos hook: tell a node agent to exit (its workers die with it)."""
        node = self.nodes.get(msg["node_id"])
        if node is None or not node.alive or node.conn is None:
            return {"ok": False}
        await node.conn.send({"type": "exit"})
        self._event("chaos_node_killed", node=node.node_id)
        return {"ok": True}

    # -------------------------------------------------------------- state
    async def h_request_resources(self, conn, meta, msg):
        """Pin an explicit capacity floor for the autoscaler (reference:
        `ray.autoscaler.sdk.request_resources` → GCS resource_request)."""
        self._explicit_demands = [
            {k: float(v) for k, v in b.items()} for b in (msg.get("bundles") or [])
        ]
        return {"ok": True}

    async def h_load_metrics(self, conn, meta, msg):
        """Demand + utilization snapshot for `StandardAutoscaler.update`
        (reference: `LoadMetrics` fed from GCS — `load_metrics.py:63`)."""
        now = time.monotonic()
        # PG-bound tasks are excluded: their capacity is already reserved by
        # the PG's bundles, so counting them would launch nodes the tasks can
        # never use (they are pinned to the bundle's node).
        pending: List[Dict[str, float]] = [
            dict(pt.spec.resources)
            for pt in list(self.ready_queue)[:1000]
            if not isinstance(
                pt.spec.options.scheduling_strategy, PlacementGroupSchedulingStrategy
            )
        ]
        # Unsatisfied direct-path lease requests are queued demand too —
        # submitters buffer client-side and retry, so without this the
        # autoscaler would see an empty queue while work waits for capacity.
        for key, (demand, unmet, ts) in list(self._lease_backlog.items()):
            if now - ts > 5.0:
                self._lease_backlog.pop(key, None)
                continue
            pending.extend(dict(demand) for _ in range(min(unmet, 100)))
        pending_pgs = []
        for pg in self.pgs.values():
            if pg["ready"]:
                continue
            # Partially-placed PGs (node death) keep surviving reservations —
            # only the unplaced slots represent new demand.
            if pg["bundle_nodes"]:
                bundles = [
                    b
                    for b, nid in zip(pg["bundles"], pg["bundle_nodes"])
                    if nid is None
                ]
                occupied = sorted(
                    {nid for nid in pg["bundle_nodes"] if nid is not None}
                )
            else:
                bundles = pg["bundles"]
                occupied = []
            if bundles:
                # `occupied` lets the autoscaler's STRICT_SPREAD packer
                # exclude surviving nodes — the controller's re-placement
                # will refuse them, so capacity there cannot satisfy this PG.
                pending_pgs.append(
                    {
                        "bundles": bundles,
                        "strategy": pg["strategy"],
                        "occupied": occupied,
                    }
                )
        # Nodes hosting live workers with work or actors are busy even when
        # they hold zero resources (default actors are 0-CPU): terminating
        # such a node would destroy the actor.
        occupied_nodes = {
            ws.node_id
            for ws in self.workers.values()
            if ws.state != DEAD
            and (ws.state == ACTOR or ws.current_task is not None)
        }
        node_report = []
        for n in self.nodes.values():
            busy = any(v < t - 1e-9 for k, t in n.total.items()
                       for v in [n.available.get(k, 0.0)]) \
                or n.spawning > 0 or n.spawning_tpu > 0 \
                or n.node_id in occupied_nodes
            node_report.append(
                {
                    "node_id": n.node_id,
                    "alive": n.alive,
                    "is_head": n.node_id == HEAD_NODE,
                    "total": dict(n.total),
                    "available": dict(n.available),
                    "idle_s": 0.0 if busy else max(0.0, now - n.last_active),
                }
            )
        return {
            "pending_demands": pending,
            "pending_pgs": pending_pgs,
            "explicit_demands": list(self._explicit_demands),
            "nodes": node_report,
        }

    async def h_cluster_resources(self, conn, meta, msg):
        total = self._cluster_totals()
        avail: Dict[str, float] = {}
        for n in self.nodes.values():
            if not n.alive:
                continue
            for k, v in n.available.items():
                avail[k] = avail.get(k, 0.0) + v
        return {"total": total, "available": avail}

    async def h_nodes(self, conn, meta, msg):
        return {
            "nodes": [
                {
                    "NodeID": n.node_id,
                    "Alive": n.alive,
                    "Labels": dict(n.labels),
                    "Resources": dict(n.total),
                    "Available": dict(n.available),
                    "NodeManagerAddress": (self.node_ip if n.node_id == HEAD_NODE else n.fetch_addr.rsplit(":", 1)[0] if n.fetch_addr else ""),
                    "object_store_memory": n.object_store_memory
                    or self.object_store_memory,
                    "SystemMetrics": dict(n.sys_metrics),
                }
                for n in self.nodes.values()
            ]
        }

    async def h_flight_pull(self, conn, meta, msg):
        """Poke every live worker to flush its flight-recorder span ring
        NOW (one-way push; drained spans arrive over the task_events
        channel). `ray-tpu flight` and /api/flight call this before
        exporting so the merged trace is current rather than up to one
        flusher period stale."""
        n = 0
        for ws in list(self.workers.values()):
            if ws.state == DEAD or ws.conn is None or ws.conn._closed:
                continue
            try:
                ws.conn.post({"type": "flight_pull"})
                n += 1
            except ConnectionError:
                pass
        return {"ok": True, "workers": n}

    async def h_state_summary(self, conn, meta, msg):
        if msg.get("counts_only"):  # cheap status — no timeline payload
            return {
                "num_workers": len([w for w in self.workers.values() if w.state != DEAD]),
                "objects": len(self.objects),
                "store_bytes": self.store_bytes_used,
                "pending_tasks": len(self.ready_queue) + len(self.waiting_tasks),
                "running_tasks": len(self.running),
            }
        return {
            "timeline": list(self.timeline[-10000:]),
            "num_workers": len([w for w in self.workers.values() if w.state != DEAD]),
            "objects": len(self.objects),
            "store_bytes": self.store_bytes_used,
            "actors": {
                h: {"state": a.state, "name": a.name} for h, a in self.actors.items()
            },
            "pending_tasks": len(self.ready_queue) + len(self.waiting_tasks),
            "running_tasks": len(self.running),
        }

    # ------------------------------------------------- state API (listing)
    # Reference analogs: `python/ray/util/state/api.py` list_* +
    # `dashboard/state_aggregator.py`. Served straight from controller state.
    async def h_list_tasks(self, conn, meta, msg):
        out = []
        for pt in list(self.ready_queue):
            out.append({"task_id": pt.spec.task_id.hex(), "name": pt.spec.name,
                        "state": "PENDING_SCHEDULING",
                        "required_resources": pt.spec.resources})
        for task_hex, pt in self.waiting_tasks.items():
            out.append({"task_id": task_hex, "name": pt.spec.name,
                        "state": "PENDING_ARGS",
                        "deps_remaining": len(pt.deps_remaining)})
        for task_hex, (worker_id, pt) in self.running.items():
            ws = self.workers.get(worker_id)
            out.append({"task_id": task_hex, "name": pt.spec.name,
                        "state": "RUNNING", "worker_id": worker_id,
                        "node_id": ws.node_id if ws else "?"})
        for task_hex, info in list(self.direct_running.items()):
            ws = self.workers.get(info.get("worker_id", ""))
            if ws is None or ws.state == DEAD:
                self.direct_running.pop(task_hex, None)  # lazily reap
                continue
            out.append({"task_id": task_hex, "name": info.get("name", ""),
                        "state": "RUNNING", "worker_id": info["worker_id"],
                        "node_id": ws.node_id, "direct": True})
        return {"tasks": out}

    async def h_list_actors(self, conn, meta, msg):
        out = []
        for h, a in self.actors.items():
            ws = self.workers.get(a.worker_id) if a.worker_id else None
            out.append({
                "actor_id": h, "state": a.state.upper(), "name": a.name,
                "namespace": a.namespace, "worker_id": a.worker_id,
                "node_id": ws.node_id if ws else None,
                "restarts": a.restarts_used,
                "pending_calls": len(a.send_queue) + len(a.inflight),
            })
        return {"actors": out}

    async def h_poll_events(self, conn, meta, msg):
        """Cursor-based event subscription over the timeline (the same feed
        `_event` writes actor_restarting/actor_death/node_died into). A
        client passes its last cursor and an optional `kinds` filter and
        gets every matching event since — the gang supervisor's
        death-notification path (docs/ELASTIC_TRAINING.md). cursor=-1 means
        "subscribe from now" (returns no events, just the tail cursor)."""
        cursor = int(msg.get("cursor", -1))
        if cursor < 0:
            return {
                "cursor": self._timeline_base + len(self.timeline),
                "events": [],
            }
        pos = cursor - self._timeline_base
        if pos > len(self.timeline):
            # Cursor from a PREVIOUS controller incarnation (restore resets
            # the timeline): re-anchor to this incarnation's BASE and replay
            # its whole feed — anchoring to the tail instead silently
            # swallowed deaths that landed during the failover gap (a gang
            # member dying while its supervisor's poll was mid-retry). A
            # same-incarnation cursor can never run ahead of the tail, so
            # this branch is unambiguous; cursors BEHIND base (trimmed
            # history) still clamp forward to base below.
            pos = 0
        idx = max(pos, 0)
        kinds = set(msg.get("kinds") or ())
        # Floor of 1: limit<=0 would never advance the cursor — a silently
        # dead subscription instead of an error.
        limit = max(1, int(msg.get("limit", 2000)))
        events = []
        tl = self.timeline
        # The cursor advances only past SCANNED entries: when `limit` stops
        # the collection early, unreturned matches stay ahead of the cursor
        # for the next poll instead of being silently skipped.
        while idx < len(tl) and len(events) < limit:
            e = tl[idx]
            if not kinds or e.get("event") in kinds:
                events.append(e)
            idx += 1
        return {"cursor": self._timeline_base + idx, "events": events}

    async def h_list_objects(self, conn, meta, msg):
        limit = msg.get("limit", 1000)
        out = []
        for h, o in itertools.islice(self.objects.items(), limit):
            out.append({
                "object_id": h, "status": o.status, "size": o.size,
                "locations": list(o.locations), "spilled": bool(o.spilled_path),
                "holders": len(o.holders), "pinned": o.pinned,
            })
        return {"objects": out, "total": len(self.objects)}

    async def h_list_placement_groups(self, conn, meta, msg):
        return {
            "placement_groups": [
                {
                    "placement_group_id": pg_hex,
                    "name": pg.get("name", ""),
                    "strategy": pg["strategy"],
                    "state": "CREATED" if pg["ready"] else "PENDING",
                    "bundles": pg["bundles"],
                    "bundle_nodes": pg["bundle_nodes"],
                }
                for pg_hex, pg in self.pgs.items()
            ]
        }

    async def h_shard_info(self, conn, meta, msg):
        """Shard-layout introspection (coordination layer): the per-shard
        actor/worker partitions and lease holders. The FT test asserts the
        cross-shard invariants on this surface — every id in exactly one
        shard, shard routing matches the hash, no lease duplicated."""
        from .control_shards import HASH_NAME, shard_of

        shards = []
        for i, sh in enumerate(self.shards):
            shards.append({
                "index": i,
                "threaded": sh.threaded,
                "actors": sorted(sh.actors),
                "workers": sorted(sh.workers),
                "leases": sorted(
                    w.worker_id for w in list(sh.workers.values())
                    if w.state == LEASED
                ),
            })
        return {"n": len(self.shards), "hash": HASH_NAME, "shards": shards}

    async def h_list_workers(self, conn, meta, msg):
        return {
            "workers": [
                {"worker_id": w.worker_id, "state": w.state, "pid": w.pid,
                 "node_id": w.node_id, "has_tpu": w.has_tpu,
                 "current_task": w.current_task, "actor": w.actor_hex,
                 "direct_addr": w.direct_addr}
                for w in self.workers.values()
            ]
        }

    # -------------------------------------------------------- log tailing
    async def h_tail_logs(self, conn, meta, msg):
        """Incremental worker-log chunks (reference analog: `log_monitor.py`
        tailing worker files → driver). cursors: {worker_id: offset}. With
        init=True, returns current end-offsets and no data (a late-joining
        driver streams from 'now' instead of replaying history). Remote-node
        workers' files live on their agent — fetched over the agent conn."""
        cursors: Dict[str, int] = msg.get("cursors", {})
        only = msg.get("worker_id")
        init = bool(msg.get("init"))
        out = {}
        from .log_utils import read_log_chunk

        def one_head(ws: WorkerState):
            # Head-node files are read synchronously: spawning a coroutine
            # per worker per poll cost ~10ms/s of pure gather overhead at
            # 2,000 workers.
            path = os.path.join(self.session_dir, f"worker-{ws.worker_id}.log")
            if init:
                try:
                    out[ws.worker_id] = {"data": "", "offset": os.path.getsize(path)}
                except OSError:
                    pass
                return
            got = read_log_chunk(path, cursors.get(ws.worker_id, 0))
            if got is not None:
                data, offset = got
                out[ws.worker_id] = {
                    "data": data.decode(errors="replace"), "offset": offset
                }

        async def one(ws: WorkerState):
            node = self.nodes.get(ws.node_id)
            if node is None or not node.alive or node.conn is None:
                return
            try:
                resp = await node.conn.request(
                    {"type": "tail_log", "worker_id": ws.worker_id,
                     "offset": cursors.get(ws.worker_id, 0), "init": init},
                    timeout=10,
                )
            except Exception:  # noqa: BLE001
                return
            if resp and resp.get("offset") is not None:
                out[ws.worker_id] = {"data": resp.get("data", ""), "offset": resp["offset"]}

        remote = []
        heads = []
        for ws in list(self.workers.values()):
            if only and ws.worker_id != only:
                continue
            if ws.node_id == HEAD_NODE:
                heads.append(ws)
            else:
                remote.append(ws)
        if heads:
            # Off-loop: one stat per worker per poll blocked the event loop
            # ~200ms at 1,000 workers (syscalls are slow on the virtualized
            # bench hosts); the scheduler must not stall behind log tailing.
            def scan():
                for ws in heads:
                    one_head(ws)

            await asyncio.get_running_loop().run_in_executor(None, scan)
        if remote:
            await asyncio.gather(*(one(ws) for ws in remote))
        return {"logs": out}

    # -------------------------------------------------- prometheus metrics
    async def h_record_metric(self, conn, meta, msg):
        """User metrics (reference: `ray.util.metrics` Counter/Gauge/Histogram
        → `metrics_agent.py` Prometheus re-export). Histograms arrive as
        client-bucketed deltas (boundaries/buckets/sum/count) and aggregate
        here into real exposition families."""
        name, kind, value = msg["name"], msg["kind"], float(msg["value"])
        tags = tuple(sorted((msg.get("tags") or {}).items()))
        key = (name, tags)
        now = time.time()
        if msg.get("help"):
            self.user_metric_help.setdefault(name, str(msg["help"]))
        if kind == "histogram":
            boundaries = tuple(float(b) for b in msg.get("boundaries") or ())
            deltas = list(msg.get("buckets") or [])
            if len(deltas) != len(boundaries) + 1:
                return None  # malformed shipment; never poison the family
            h = self.user_hists.get(key)
            if h is None or h["boundaries"] != boundaries:
                # New series (or a reconfigured client changed boundaries —
                # restart the series rather than merging incompatible grids).
                h = self.user_hists[key] = {
                    "boundaries": boundaries,
                    "buckets": [0] * (len(boundaries) + 1),
                    "sum": 0.0, "count": 0,
                }
            h["buckets"] = [a + int(b) for a, b in zip(h["buckets"], deltas)]
            h["sum"] += float(msg.get("sum") or 0.0)
            h["count"] += int(msg.get("count") or 0)
            h["ts"] = now
        elif kind == "counter":
            cur = self.user_metrics.get(key, (0.0, None, 0.0))[0]
            self.user_metrics[key] = (cur + value, kind, now)
        else:  # gauge
            self.user_metrics[key] = (value, kind, now)
        return None

    async def h_prune_metrics(self, conn, meta, msg):
        """Drop user-metric series whose tags include all of msg['tags'] —
        called when a Serve replica drains so its gauges/histograms leave
        /metrics immediately instead of waiting out the staleness window."""
        match = {str(k): str(v) for k, v in (msg.get("tags") or {}).items()}
        if not match:
            return None
        for d in (self.user_metrics, self.user_hists):
            for key in [k for k in d if match.items() <= dict(k[1]).items()]:
                del d[key]
        return None

    def _prune_stale_metrics(self, now: float):
        cut = now - self._metric_staleness_s
        for key in [k for k, v in self.user_metrics.items() if v[2] < cut]:
            del self.user_metrics[key]
        for key in [k for k, v in self.user_hists.items() if v.get("ts", now) < cut]:
            del self.user_hists[key]

    def _prometheus_text(self) -> str:
        now = time.time()
        if self._wal is not None:
            # Scrape-time refresh (also keeps the gauge out of the
            # staleness sweep while the WAL lives).
            self._self_set_gauge(
                "controller_log_bytes", float(self._wal.total_bytes())
            )
        self._prune_stale_metrics(now)
        lines = [
            "# TYPE ray_tpu_tasks_pending gauge",
            f"ray_tpu_tasks_pending {len(self.ready_queue) + len(self.waiting_tasks)}",
            "# TYPE ray_tpu_tasks_running gauge",
            f"ray_tpu_tasks_running {len(self.running)}",
            "# TYPE ray_tpu_objects gauge",
            f"ray_tpu_objects {len(self.objects)}",
            "# TYPE ray_tpu_object_store_bytes gauge",
            f"ray_tpu_object_store_bytes {self.store_bytes_used}",
            "# TYPE ray_tpu_workers_alive gauge",
            f"ray_tpu_workers_alive {sum(1 for w in self.workers.values() if w.state != DEAD)}",
            "# TYPE ray_tpu_nodes_alive gauge",
            f"ray_tpu_nodes_alive {sum(1 for n in self.nodes.values() if n.alive)}",
            "# TYPE ray_tpu_actors gauge",
            f"ray_tpu_actors {sum(1 for a in self.actors.values() if a.state == 'alive')}",
        ]
        node_families: Dict[str, List[str]] = {}
        for n in self.nodes.values():
            if not n.alive:
                continue
            for k, v in n.available.items():
                node_families.setdefault("ray_tpu_node_resource_available", []).append(
                    f'ray_tpu_node_resource_available{{node="{_esc_label(n.node_id)}",'
                    f'resource="{_esc_label(k)}"}} {v}'
                )
            for k, v in n.sys_metrics.items():
                if k == "ts":
                    continue
                fam = _san_name(f"ray_tpu_node_{k}")
                node_families.setdefault(fam, []).append(
                    f'{fam}{{node="{_esc_label(n.node_id)}"}} {v}'
                )
        for fam, series in node_families.items():
            lines.append(f"# TYPE {fam} gauge")
            lines.extend(series)

        # User scalars, grouped into families so every series sits under one
        # # HELP/# TYPE header (scrapers misclassify bare counters otherwise).
        scalar_fams: Dict[str, List[Tuple[tuple, float]]] = {}
        fam_kind: Dict[str, str] = {}
        fam_raw: Dict[str, str] = {}
        for (name, tags), (value, kind, _ts) in self.user_metrics.items():
            fam = _san_name(name)
            scalar_fams.setdefault(fam, []).append((tags, value))
            fam_kind.setdefault(fam, kind)
            fam_raw.setdefault(fam, name)
        for fam, series in scalar_fams.items():
            help_text = self.user_metric_help.get(fam_raw[fam])
            if help_text:
                lines.append(f"# HELP {fam} {_esc_help(help_text)}")
            lines.append(f"# TYPE {fam} {fam_kind[fam] or 'gauge'}")
            for tags, value in series:
                tag_s = _format_tags(tags)
                lines.append(f"{fam}{{{tag_s}}} {value}" if tag_s else f"{fam} {value}")

        # Histograms: cumulative _bucket{le=...} + _sum + _count per series.
        hist_fams: Dict[str, List[Tuple[tuple, dict]]] = {}
        hist_raw: Dict[str, str] = {}
        for (name, tags), h in self.user_hists.items():
            fam = _san_name(name)
            hist_fams.setdefault(fam, []).append((tags, h))
            hist_raw.setdefault(fam, name)
        for fam, series in hist_fams.items():
            help_text = self.user_metric_help.get(hist_raw[fam])
            if help_text:
                lines.append(f"# HELP {fam} {_esc_help(help_text)}")
            lines.append(f"# TYPE {fam} histogram")
            for tags, h in series:
                tag_s = _format_tags(tags)
                cum = 0
                for b, cnt in zip(h["boundaries"], h["buckets"]):
                    cum += cnt
                    le = _format_le(b)
                    sep = "," if tag_s else ""
                    lines.append(f'{fam}_bucket{{{tag_s}{sep}le="{le}"}} {cum}')
                sep = "," if tag_s else ""
                lines.append(f'{fam}_bucket{{{tag_s}{sep}le="+Inf"}} {h["count"]}')
                lines.append(
                    f"{fam}_sum{{{tag_s}}} {h['sum']}" if tag_s else f"{fam}_sum {h['sum']}"
                )
                lines.append(
                    f"{fam}_count{{{tag_s}}} {h['count']}" if tag_s
                    else f"{fam}_count {h['count']}"
                )
        return "\n".join(lines) + "\n"

    async def _on_metrics_connection(self, reader, writer):
        """Minimal HTTP/1.0 responder for GET /metrics (Prometheus text)."""
        try:
            line = await asyncio.wait_for(reader.readline(), 5)
            while True:  # drain headers
                h = await asyncio.wait_for(reader.readline(), 5)
                if h in (b"\r\n", b"\n", b""):
                    break
            body = self._prometheus_text().encode()
            path = line.split(b" ")[1] if len(line.split(b" ")) > 1 else b"/"
            if not path.startswith(b"/metrics"):
                writer.write(b"HTTP/1.0 404 Not Found\r\nContent-Length: 0\r\n\r\n")
            else:
                writer.write(
                    b"HTTP/1.0 200 OK\r\nContent-Type: text/plain; version=0.0.4\r\n"
                    + f"Content-Length: {len(body)}\r\n\r\n".encode()
                    + body
                )
            await writer.drain()
        except Exception:  # noqa: BLE001
            pass
        finally:
            writer.close()

    def _trim_timeline(self):
        """Cap + cursor-base bookkeeping MUST move together: dropping
        entries without advancing _timeline_base would silently shift
        every poll_events cursor by the truncation amount."""
        if len(self.timeline) > 100_000:
            del self.timeline[:50_000]
            self._timeline_base += 50_000

    # High-volume lifecycle kinds subject to the storm cap (the task-events
    # 4096-cap pattern applied to the ACTOR lifecycle): a 10k-actor wave
    # must not spend its controller time narrating itself into the
    # timeline. Death/restart/failure kinds are EXEMPT — poll_events
    # subscribers (the elastic-training gang supervisor) depend on them.
    _STORM_KINDS = frozenset({
        "worker_spawn", "worker_registered", "actor_created", "actor_alive",
        "actor_readopted", "task_submitted", "task_dispatched", "task_done",
        "task_handoff", "lease_granted",
    })
    _STORM_WINDOW_S = 1.0
    _STORM_CAP = 4096

    def _event(self, kind: str, **fields):
        if kind in self._STORM_KINDS:
            now = time.monotonic()
            st = getattr(self, "_storm_state", None)
            if st is None:
                st = self._storm_state = [now, 0, 0]  # window t0, count, dropped
            if now - st[0] >= self._STORM_WINDOW_S:
                if st[2]:
                    self.timeline.append({
                        "ts": time.time(), "event": "actor_events_dropped",
                        "n": st[2],
                    })
                st[0], st[1], st[2] = now, 0, 0
            st[1] += 1
            if st[1] > self._STORM_CAP:
                st[2] += 1
                return
        self.timeline.append({"ts": time.time(), "event": kind, **fields})
        self._trim_timeline()


async def run_controller(args: dict):
    ctrl = Controller(
        num_cpus=args["num_cpus"],
        resources=args.get("resources", {}),
        session_dir=args["session_dir"],
        object_store_memory=args.get("object_store_memory"),
        port=args.get("port", 0),
        standalone=bool(args.get("standalone")),
    )
    await ctrl.start(restore=bool(args.get("restore")))
    # Handshake: parent reads this line to learn the port.
    print(f"RAY_TPU_CONTROLLER_PORT={ctrl.port}", flush=True)
    await ctrl.serve_forever()
