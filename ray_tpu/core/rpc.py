"""Minimal async message-passing layer for the control plane.

Reference analog: `src/ray/rpc` (gRPC wrappers). Here: length-prefixed
pickled dicts over TCP (loopback) — the control plane carries only small
metadata messages; bulk data rides the shared-memory object store
(`store.py`), mirroring the reference's plasma/gRPC split.

Wire format: [u32 length][pickle(dict)]. Every message dict has:
    type: str           — handler name
    req_id: int | None  — set for request/response pairs
Responses echo req_id with type="__response__".
"""

from __future__ import annotations

import asyncio
import itertools
import pickle
import struct
import threading
from typing import Any, Awaitable, Callable, Dict, Optional

_LEN = struct.Struct("<I")


async def read_msg(reader: asyncio.StreamReader) -> dict:
    header = await reader.readexactly(4)
    (n,) = _LEN.unpack(header)
    body = await reader.readexactly(n)
    return pickle.loads(body)


def encode_msg(msg: dict) -> bytes:
    body = pickle.dumps(msg, protocol=5)
    return _LEN.pack(len(body)) + body


class Connection:
    """One side of a persistent connection; request/response + push handling."""

    def __init__(
        self,
        reader: asyncio.StreamReader,
        writer: asyncio.StreamWriter,
        on_push: Optional[Callable[[dict], Awaitable[None]]] = None,
        on_close: Optional[Callable[[], Awaitable[None]]] = None,
    ):
        self.reader = reader
        self.writer = writer
        self.on_push = on_push
        self.on_close = on_close
        self._req_ids = itertools.count(1)
        self._pending: Dict[int, asyncio.Future] = {}
        self._write_lock = asyncio.Lock()
        self._closed = False
        self._reader_task: Optional[asyncio.Task] = None

    def start(self):
        self._reader_task = asyncio.get_running_loop().create_task(self._read_loop())

    async def _read_loop(self):
        try:
            while True:
                msg = await read_msg(self.reader)
                if msg.get("type") == "__response__":
                    fut = self._pending.pop(msg["req_id"], None)
                    if fut is not None and not fut.done():
                        fut.set_result(msg.get("payload"))
                elif self.on_push is not None:
                    await self.on_push(msg)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        finally:
            self._closed = True
            for fut in self._pending.values():
                if not fut.done():
                    fut.set_exception(ConnectionError("connection closed"))
            self._pending.clear()
            if self.on_close is not None:
                try:
                    await self.on_close()
                except Exception:  # noqa: BLE001
                    pass

    async def send(self, msg: dict):
        """One-way message."""
        async with self._write_lock:
            self.writer.write(encode_msg(msg))
            await self.writer.drain()

    async def request(self, msg: dict, timeout: Optional[float] = None) -> Any:
        req_id = next(self._req_ids)
        msg = dict(msg, req_id=req_id)
        fut = asyncio.get_running_loop().create_future()
        self._pending[req_id] = fut
        await self.send(msg)
        if timeout is None:
            return await fut
        return await asyncio.wait_for(fut, timeout)

    async def respond(self, req_id: int, payload: Any):
        await self.send({"type": "__response__", "req_id": req_id, "payload": payload})

    def close(self):
        self._closed = True
        try:
            self.writer.close()
        except Exception:  # noqa: BLE001
            pass


class EventLoopThread:
    """A dedicated asyncio loop on a daemon thread; sync entrypoints for clients."""

    def __init__(self, name: str = "rtpu-io"):
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._run, name=name, daemon=True)
        self._thread.start()

    def _run(self):
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    def call(self, coro, timeout: Optional[float] = None):
        """Run a coroutine on the loop from a sync thread; block for result."""
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def call_nowait(self, coro):
        return asyncio.run_coroutine_threadsafe(coro, self.loop)

    def stop(self):
        self.loop.call_soon_threadsafe(self.loop.stop)
        self._thread.join(timeout=2)
