"""Normalization ops: RMSNorm / LayerNorm with a fused Pallas path on TPU.

XLA fuses these adequately in most cases; the Pallas path exists for the
(seq*batch, hidden) hot shape where keeping the row resident in VMEM for the
two passes (stats + scale) avoids an HBM round trip.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def _rmsnorm_ref(x, w, eps):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    y = x.astype(jnp.float32) * jax.lax.rsqrt(var + eps)
    return (y * w.astype(jnp.float32)).astype(x.dtype)


def _rmsnorm_kernel(x_ref, w_ref, o_ref, *, eps: float):
    x = x_ref[:].astype(jnp.float32)
    var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
    y = x * jax.lax.rsqrt(var + eps)
    o_ref[:] = (y * w_ref[:].astype(jnp.float32)).astype(o_ref.dtype)


def _rmsnorm_pallas(x2d, w, eps, block_rows=256):
    from jax.experimental import pallas as pl

    N, D = x2d.shape
    block_rows = min(block_rows, N)
    return pl.pallas_call(
        functools.partial(_rmsnorm_kernel, eps=eps),
        out_shape=jax.ShapeDtypeStruct((N, D), x2d.dtype),
        grid=(pl.cdiv(N, block_rows),),
        in_specs=[
            pl.BlockSpec((block_rows, D), lambda i: (i, 0)),
            pl.BlockSpec((D,), lambda i: (0,)),
        ],
        out_specs=pl.BlockSpec((block_rows, D), lambda i: (i, 0)),
    )(x2d, w)


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _rmsnorm(x, w, eps):
    from .attention import _on_tpu

    shape = x.shape
    if _on_tpu() and shape[-1] % 128 == 0:
        x2d = x.reshape(-1, shape[-1])
        return _rmsnorm_pallas(x2d, w, eps).reshape(shape)
    return _rmsnorm_ref(x, w, eps)


def _rmsnorm_fwd(x, w, eps):
    return _rmsnorm(x, w, eps), (x, w)


def _rmsnorm_bwd(eps, res, g):
    x, w = res
    _, vjp = jax.vjp(lambda x_, w_: _rmsnorm_ref(x_, w_, eps), x, w)
    return vjp(g)


_rmsnorm.defvjp(_rmsnorm_fwd, _rmsnorm_bwd)


def rmsnorm(x, weight, eps: float = 1e-6):
    return _rmsnorm(x, weight, eps)


def layernorm(x, weight, bias: Optional[jnp.ndarray] = None, eps: float = 1e-5):
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.var(xf, axis=-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps) * weight.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(x.dtype)
