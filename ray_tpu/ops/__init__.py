from .attention import (
    attention_reference,
    flash_attention,
    ring_attention,
    ulysses_attention,
)
from .norms import layernorm, rmsnorm
from .rope import apply_rope, rope_frequencies

__all__ = [
    "flash_attention",
    "ring_attention",
    "ulysses_attention",
    "attention_reference",
    "rmsnorm",
    "layernorm",
    "apply_rope",
    "rope_frequencies",
]
