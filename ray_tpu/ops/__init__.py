from .attention import (
    attention_reference,
    flash_attention,
    ring_attention,
    ulysses_attention,
)
from .moe import MoEConfig, moe_forward, moe_init, moe_router
from .norms import layernorm, rmsnorm
from .rope import apply_rope, rope_frequencies

__all__ = [
    "flash_attention",
    "ring_attention",
    "ulysses_attention",
    "attention_reference",
    "rmsnorm",
    "layernorm",
    "apply_rope",
    "rope_frequencies",
    "MoEConfig",
    "moe_init",
    "moe_forward",
    "moe_router",
]
