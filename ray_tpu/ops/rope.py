"""Rotary position embeddings (RoPE). Pure jnp — XLA fuses this into the
surrounding matmuls; a kernel would add nothing."""

from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(head_dim: int, max_seq: int, theta: float = 10000.0, dtype=jnp.float32):
    """Precompute (cos, sin) tables of shape [max_seq, head_dim//2]."""
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    pos = jnp.arange(max_seq, dtype=jnp.float32)
    ang = jnp.outer(pos, inv)
    return jnp.cos(ang).astype(dtype), jnp.sin(ang).astype(dtype)


def apply_rope(x, cos, sin, positions=None):
    """x: [..., seq, head_dim]; cos/sin: [max_seq, head_dim//2].

    Rotates pairs (x[2i], x[2i+1]) — GPT-NeoX/Llama convention via
    half-split (equivalent under a fixed permutation of dims).
    """
    seq = x.shape[-2]
    if positions is None:
        c = cos[:seq]
        s = sin[:seq]
    else:
        c = cos[positions]
        s = sin[positions]
    # Broadcast [seq, hd/2] across leading dims.
    while c.ndim < x.ndim:
        c = c[None]
        s = s[None]
    x1, x2 = jnp.split(x, 2, axis=-1)
    out = jnp.concatenate([x1 * c - x2 * s, x2 * c + x1 * s], axis=-1)
    return out.astype(x.dtype)
