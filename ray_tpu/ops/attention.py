"""Attention kernels: Pallas flash attention + ring/Ulysses context parallelism.

The reference has NO sequence-parallel attention (SURVEY.md §2.6 — grep shows
long-context entirely delegated to DeepSpeed/FSDP inside Train workers). Here
it is first-class:

  * `flash_attention` — blockwise online-softmax kernel on the MXU
    (Pallas; falls back to an XLA reference off-TPU).
  * `ring_attention`  — sequence shards on the `sp` mesh axis; K/V blocks
    rotate around the ring via `ppermute` with global-position causal
    masking and online-softmax merging. Call under `shard_map`.
  * `ulysses_attention` — all_to_all head<->seq exchange so each device
    runs full-sequence attention on a head subset.

Shapes follow [batch, heads, seq, head_dim] throughout.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


# --------------------------------------------------------------- reference
def attention_reference(q, k, v, causal: bool = True, sm_scale: Optional[float] = None):
    """XLA attention (materializes logits). Ground truth for kernels and the
    off-TPU fallback."""
    *_, S, D = q.shape
    Skv = k.shape[-2]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(D)
    logits = jnp.einsum("bhsd,bhtd->bhst", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale
    if causal:
        qpos = jnp.arange(S)[:, None] + (Skv - S)  # align ends when S != Skv
        kpos = jnp.arange(Skv)[None, :]
        logits = jnp.where(qpos >= kpos, logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhst,bhtd->bhsd", probs.astype(v.dtype), v)


# ------------------------------------------------------------ pallas kernel
#
# All three kernels stream K/V (or Q for dk/dv) block-by-block from HBM via a
# third grid axis instead of holding the whole sequence in VMEM: grid =
# (batch*heads, outer blocks, streamed blocks), with the running accumulators
# in VMEM scratch that persists across the innermost ("arbitrary") axis.
# VMEM per step is O(block) not O(S), so a single chip runs S=16k+ (the old
# whole-KV layout hit the 16 MiB scoped-vmem wall at 16k — VERDICT r3 §weak 1).
# Causal skipping: the streamed index map clamps past-diagonal steps to the
# last relevant block — Pallas skips the DMA when consecutive steps map to the
# same block — and `pl.when` skips the compute.


def _causal_last_kv(qi, block_q, block_k, row_offset, nk):
    """Index of the last K/V block the causal mask lets q block `qi` touch."""
    last = jax.lax.div(row_offset + (qi + 1) * block_q - 1, block_k)
    return jnp.clip(last, 0, nk - 1)


def _causal_first_q(ki, block_q, block_k, row_offset, nq):
    """Index of the first q block whose rows reach k block `ki` (causal)."""
    first = jax.lax.div(ki * block_k - row_offset, block_q)
    return jnp.clip(first, 0, nq - 1)


def _flash_fwd_kernel(
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    *rest,  # ([lse_ref,] acc_ref, m_ref, l_ref) — lse only on the training path
    block_k: int,
    causal: bool,
    sm_scale: float,
    seq_q: int,
    seq_kv: int,
):
    """One (q block, k block) grid step of the online-softmax forward.

    Inputs are PADDED to block multiples by the caller (pl.ds on a ragged
    tail clamps the start index, silently misaligning data vs mask — so
    padding + masking against the ORIGINAL lengths is the only safe layout).
    seq_q/seq_kv are the original (unpadded) lengths."""
    from jax.experimental import pallas as pl

    if len(rest) == 4:
        lse_ref, acc_ref, m_ref, l_ref = rest
    else:
        lse_ref = None
        acc_ref, m_ref, l_ref = rest

    qi = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)
    nk_static = nk if isinstance(nk, int) else 0  # grid is static in practice
    block_q = q_ref.shape[1]
    # When S != Skv (decode over a cached prefix) queries are END-aligned
    # with keys, matching attention_reference's (Skv - S) offset.
    row_offset = seq_kv - seq_q

    if nk_static != 1:
        @pl.when(j == 0)
        def _init():
            acc_ref[...] = jnp.zeros_like(acc_ref)
            m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
            l_ref[...] = jnp.zeros_like(l_ref)

    block_k_pad = k_ref.shape[1]
    # Masking is pure VPU cost (2 iotas + 2 compares + where per element) and
    # only EDGE blocks need it: the diagonal block (causal) and the ragged
    # tail (padding). Interior blocks take the unmasked fast path — at long S
    # that's nearly all of them, and the kernel is VPU-bound (VERDICT r3).
    kv_ragged = (seq_kv % block_k_pad) != 0
    last_kv_block = (seq_kv + block_k_pad - 1) // block_k_pad - 1

    def _softmax_update(s, v_blk):
        m_prev = m_ref[...]
        m_cur = jnp.max(s, axis=-1, keepdims=True)  # [Bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[...] = m_new
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    def _logits():
        # Keep MXU operands in the input dtype (bf16 runs the MXU at full
        # rate; an f32 upcast quarters matmul throughput). f32 only for stats.
        q = q_ref[0]      # [Bq, D]
        k_blk = k_ref[0]  # [Bk, D]
        return jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale  # [Bq, Bk] f32

    # A block needs a mask iff the causal diagonal crosses it or it holds the
    # padded tail. Below-diagonal interior blocks are fully valid.
    if causal:
        diag = _causal_last_kv(qi, block_q, block_k, row_offset, nk)
        # Fully valid iff the block's last col is ≤ the q block's FIRST row —
        # with block_k < block_q several blocks straddle the diagonal band.
        below_band = ((j + 1) * block_k - 1) <= (row_offset + qi * block_q)
        on_edge = jnp.logical_or(
            jnp.logical_not(below_band),
            jnp.logical_and(kv_ragged, j == last_kv_block),
        )
        in_range = j <= diag
    else:
        on_edge = jnp.logical_and(kv_ragged, j == last_kv_block) if kv_ragged else False
        in_range = True

    def _masked_logits():
        s = _logits()
        cols = j * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        valid = cols < seq_kv  # mask the zero-padded tail
        if causal:
            rows = (
                row_offset + qi * block_q
                + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            )
            valid = jnp.logical_and(valid, rows >= cols)
        return jnp.where(valid, s, _NEG_INF)

    if nk_static == 1:
        # Whole K/V fits one grid step (short sequences): skip the online-
        # softmax scratch round-trips entirely — plain softmax in registers.
        s = _masked_logits() if (causal or kv_ragged) else _logits()
        m = jnp.max(s, axis=-1, keepdims=True)
        p = jnp.exp(s - m)
        l = jnp.sum(p, axis=-1, keepdims=True)
        acc = jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        if lse_ref is not None:
            lse_ref[0, 0, pl.ds(qi * block_q, block_q)] = (
                m + jnp.log(jnp.maximum(l, 1e-30))
            )[:, 0]
        return

    if causal or kv_ragged:
        @pl.when(jnp.logical_and(in_range, jnp.logical_not(on_edge)))
        def _fast():
            _softmax_update(_logits(), v_ref[0])

        @pl.when(jnp.logical_and(in_range, on_edge))
        def _masked():
            _softmax_update(_masked_logits(), v_ref[0])
    else:
        _softmax_update(_logits(), v_ref[0])

    @pl.when(j == nk - 1)
    def _flush():
        l = l_ref[...]
        o_ref[0] = (acc_ref[...] / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
        if lse_ref is not None:
            # logsumexp per row — the only softmax statistic backward needs.
            # The lse block is the full (1, 1, S_p) row; each qi writes its
            # slice, covering S_p by the time the bh block flushes.
            lse_ref[0, 0, pl.ds(qi * block_q, block_q)] = (
                m_ref[...] + jnp.log(jnp.maximum(l, 1e-30))
            )[:, 0]


def _compiler_params(pltpu, semantics=("parallel", "arbitrary")):
    if hasattr(pltpu, "CompilerParams"):
        return pltpu.CompilerParams(dimension_semantics=semantics)
    return None


def _flash_fwd_pallas(q, k, v, causal: bool, sm_scale: float, block_q: int, block_k: int,
                      interpret: bool = False, return_lse: bool = False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, S, D = q.shape
    Skv = k.shape[2]
    block_q = min(block_q, max(S, 8))
    block_k = min(block_k, Skv)
    # Pad to block multiples (see kernel docstring for why).
    S_p = -(-S // block_q) * block_q
    Skv_p = -(-Skv // block_k) * block_k
    qr = q.reshape(B * H, S, D)
    kr = k.reshape(B * H, Skv, D)
    vr = v.reshape(B * H, Skv, D)
    if S_p != S:
        qr = jnp.pad(qr, ((0, 0), (0, S_p - S), (0, 0)))
    if Skv_p != Skv:
        kr = jnp.pad(kr, ((0, 0), (0, Skv_p - Skv), (0, 0)))
        vr = jnp.pad(vr, ((0, 0), (0, Skv_p - Skv), (0, 0)))
    nq = S_p // block_q
    nk = Skv_p // block_k
    row_offset = Skv - S
    grid = (B * H, nq, nk)  # kv innermost: scratch accumulates across it

    if causal:
        # Past-diagonal steps re-map to the last relevant block: same index as
        # the previous step ⇒ Pallas skips the DMA; pl.when skips the compute.
        def kv_index(bh, i, j):
            return (bh, jnp.minimum(j, _causal_last_kv(i, block_q, block_k, row_offset, nk)), 0)
    else:
        def kv_index(bh, i, j):
            return (bh, j, 0)

    out_shape = [jax.ShapeDtypeStruct((B * H, S_p, D), q.dtype)]
    out_specs = [pl.BlockSpec((1, block_q, D), lambda bh, i, j: (bh, i, 0))]
    if return_lse:  # inference forward skips the lse compute+HBM write
        out_shape.append(jax.ShapeDtypeStruct((B * H, 1, S_p), jnp.float32))
        out_specs.append(pl.BlockSpec((1, 1, S_p), lambda bh, i, j: (bh, 0, 0)))
    # The training path's lse output is ONE (1,1,S_p) block revisited by
    # every q-block step — its grid dim must stay "arbitrary" or a megacore
    # partition would write back per-core copies of the shared row.
    q_dim_semantics = "arbitrary" if return_lse else "parallel"
    res = pl.pallas_call(
        functools.partial(
            _flash_fwd_kernel,
            block_k=block_k,
            causal=causal,
            sm_scale=sm_scale,
            seq_q=S,
            seq_kv=Skv,
        ),
        out_shape=tuple(out_shape),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_k, D), kv_index),
            pl.BlockSpec((1, block_k, D), kv_index),
        ],
        out_specs=tuple(out_specs),
        scratch_shapes=[
            pltpu.VMEM((block_q, D), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        compiler_params=_compiler_params(pltpu, ("parallel", q_dim_semantics, "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=4 * B * H * S * Skv * D,
            bytes_accessed=2 * (qr.size + kr.size + vr.size) * q.dtype.itemsize,
            transcendentals=B * H * S * Skv,
        ),
        interpret=interpret,
    )(qr, kr, vr)
    out = res[0][:, :S].reshape(B, H, S, D)
    if return_lse:
        return out, res[1]  # lse stays padded/flat — backward consumes it as-is
    return out


def _flash_bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc_ref,
    *, block_k: int, causal: bool, sm_scale: float, seq_q: int, seq_kv: int,
):
    """dQ for one q block: stream k blocks up to the causal diagonal.

    FlashAttention-2 backward: P = exp(S - lse); dS = P∘(dO·Vᵀ − Δ);
    dQ = scale · dS·K, with Δ = rowsum(dO∘O) precomputed by the caller.
    """
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    j = pl.program_id(2)
    nk = pl.num_programs(2)
    block_q = q_ref.shape[1]
    row_offset = seq_kv - seq_q

    @pl.when(j == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    def _guard(fn):
        if causal:
            return pl.when(j <= _causal_last_kv(qi, block_q, block_k, row_offset, nk))(fn)
        return fn()

    @_guard
    def _body():
        q = q_ref[0]    # bf16 — MXU operands stay in input dtype
        do = do_ref[0]
        lse = lse_ref[0, 0][:, None]      # [Bq, 1]
        delta = delta_ref[0, 0][:, None]  # [Bq, 1]
        k_blk = k_ref[0]
        v_blk = v_ref[0]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        cols = j * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        valid = cols < seq_kv
        if causal:
            rows = (
                row_offset + qi * block_q
                + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            )
            valid = jnp.logical_and(valid, rows >= cols)
        p = jnp.where(valid, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = (p * (dp - delta)).astype(k_blk.dtype)
        dq_acc_ref[...] = dq_acc_ref[...] + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(j == nk - 1)
    def _flush():
        dq_ref[0] = (dq_acc_ref[...] * sm_scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_acc_ref, dv_acc_ref,
    *, block_q: int, causal: bool, sm_scale: float, seq_q: int, seq_kv: int,
):
    """dK/dV for one k block: stream q blocks from the causal diagonal down.

    dV = Pᵀ·dO ; dK = scale · dSᵀ·Q. Padded q rows contribute nothing because
    dO and Δ are zero-padded there (dS = P∘(0 − 0) = 0)."""
    from jax.experimental import pallas as pl

    ki = pl.program_id(1)
    i = pl.program_id(2)
    nq = pl.num_programs(2)
    block_k = k_ref.shape[1]
    row_offset = seq_kv - seq_q

    @pl.when(i == 0)
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    def _guard(fn):
        if causal:
            return pl.when(i >= _causal_first_q(ki, block_q, block_k, row_offset, nq))(fn)
        return fn()

    @_guard
    def _body():
        k = k_ref[0]  # bf16 — MXU operands stay in input dtype
        v = v_ref[0]
        q_blk = q_ref[0]
        do_blk = do_ref[0]
        lse = lse_ref[0, 0][:, None]
        delta = delta_ref[0, 0][:, None]
        s = jax.lax.dot_general(
            q_blk, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale  # [Bq, Bk]
        cols = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        valid = cols < seq_kv
        rows_abs = i * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        # Padded q rows must not reach p: exp against a padded-row lse can
        # overflow to inf, and inf · 0 (zero-padded dO) would make NaNs.
        valid = jnp.logical_and(valid, rows_abs < seq_q)
        if causal:
            valid = jnp.logical_and(valid, rows_abs + row_offset >= cols)
        p = jnp.where(valid, jnp.exp(s - lse), 0.0)
        pb = p.astype(do_blk.dtype)
        dv_acc_ref[...] = dv_acc_ref[...] + jax.lax.dot_general(
            pb, do_blk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do_blk, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = (p * (dp - delta)).astype(q_blk.dtype)
        dk_acc_ref[...] = dk_acc_ref[...] + jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(i == nq - 1)
    def _flush():
        dk_ref[0] = (dk_acc_ref[...] * sm_scale).astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[...].astype(dv_ref.dtype)


def _flash_bwd_pallas(q, k, v, o, lse, g, causal: bool, sm_scale: float,
                      block_q: int, block_k: int, interpret: bool = False):
    """Flash backward: two Pallas passes (dq over q blocks; dk/dv over k
    blocks) against the saved logsumexp — no S×S materialization. Replaces
    the round-1 full-logit XLA fallback (VERDICT.md "What's weak" #1)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, S, D = q.shape
    Skv = k.shape[2]
    block_q = min(block_q, max(S, 8))
    block_k = min(block_k, Skv)
    S_p = -(-S // block_q) * block_q
    Skv_p = -(-Skv // block_k) * block_k

    # Δ = rowsum(dO ∘ O) — cheap elementwise, XLA fuses it.
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)

    qr = q.reshape(B * H, S, D)
    kr = k.reshape(B * H, Skv, D)
    vr = v.reshape(B * H, Skv, D)
    gr = g.reshape(B * H, S, D)
    dr = delta.reshape(B * H, 1, S)
    if S_p != S:
        qr = jnp.pad(qr, ((0, 0), (0, S_p - S), (0, 0)))
        gr = jnp.pad(gr, ((0, 0), (0, S_p - S), (0, 0)))
        dr = jnp.pad(dr, ((0, 0), (0, 0), (0, S_p - S)))
    if Skv_p != Skv:
        kr = jnp.pad(kr, ((0, 0), (0, Skv_p - Skv), (0, 0)))
        vr = jnp.pad(vr, ((0, 0), (0, Skv_p - Skv), (0, 0)))
    # lse arrives padded to (BH, 1, S_p) from the forward (same block_q).
    lr = lse

    nq = S_p // block_q
    nk = Skv_p // block_k
    row_offset = Skv - S
    kwargs = dict(causal=causal, sm_scale=sm_scale, seq_q=S, seq_kv=Skv)

    if causal:
        def kv_index(bh, i, j):
            return (bh, jnp.minimum(j, _causal_last_kv(i, block_q, block_k, row_offset, nk)), 0)

        def q_index(bh, ki, i):
            return (bh, jnp.maximum(i, _causal_first_q(ki, block_q, block_k, row_offset, nq)), 0)
    else:
        def kv_index(bh, i, j):
            return (bh, j, 0)

        def q_index(bh, ki, i):
            return (bh, i, 0)

    def q_row_index(bh, ki, i):
        return (bh, 0, q_index(bh, ki, i)[1])

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_k=block_k, **kwargs),
        out_shape=jax.ShapeDtypeStruct((B * H, S_p, D), q.dtype),
        grid=(B * H, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_k, D), kv_index),
            pl.BlockSpec((1, block_k, D), kv_index),
            pl.BlockSpec((1, block_q, D), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda bh, i, j: (bh, 0, i)),
            pl.BlockSpec((1, 1, block_q), lambda bh, i, j: (bh, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda bh, i, j: (bh, i, 0)),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        compiler_params=_compiler_params(pltpu, ("parallel", "parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=6 * B * H * S * Skv * D,
            bytes_accessed=3 * (qr.size + kr.size + vr.size) * q.dtype.itemsize,
            transcendentals=B * H * S * Skv,
        ),
        interpret=interpret,
    )(qr, kr, vr, gr, lr, dr)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, block_q=block_q, **kwargs),
        out_shape=(
            jax.ShapeDtypeStruct((B * H, Skv_p, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, Skv_p, D), v.dtype),
        ),
        grid=(B * H, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, D), q_index),
            pl.BlockSpec((1, block_k, D), lambda bh, ki, i: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, ki, i: (bh, ki, 0)),
            pl.BlockSpec((1, block_q, D), q_index),
            pl.BlockSpec((1, 1, block_q), q_row_index),
            pl.BlockSpec((1, 1, block_q), q_row_index),
        ],
        out_specs=(
            pl.BlockSpec((1, block_k, D), lambda bh, ki, i: (bh, ki, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, ki, i: (bh, ki, 0)),
        ),
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        compiler_params=_compiler_params(pltpu, ("parallel", "parallel", "arbitrary")),
        cost_estimate=pl.CostEstimate(
            flops=8 * B * H * S * Skv * D,  # 4 matmuls: s, dv, dp, dk
            bytes_accessed=3 * (qr.size + kr.size + vr.size) * q.dtype.itemsize,
            transcendentals=B * H * S * Skv,
        ),
        interpret=interpret,
    )(qr, kr, vr, gr, lr, dr)

    dq = dq[:, :S].reshape(B, H, S, D)
    dk = dk[:, :Skv].reshape(B, H, Skv, D)
    dv = dv[:, :Skv].reshape(B, H, Skv, D)
    return dq, dk, dv


def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:  # noqa: BLE001
        return False


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, sm_scale, block_q, block_k):
    return _flash_fwd_pallas(q, k, v, causal, sm_scale, block_q, block_k)


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    out, lse = _flash_fwd_pallas(
        q, k, v, causal, sm_scale, block_q, block_k, return_lse=True
    )
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, sm_scale, block_q, block_k, res, g):
    q, k, v, o, lse = res
    return _flash_bwd_pallas(q, k, v, o, lse, g, causal, sm_scale, block_q, block_k)


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_stats(q, k, v, causal, sm_scale, block_q, block_k):
    return _flash_fwd_pallas(
        q, k, v, causal, sm_scale, block_q, block_k, return_lse=True
    )


def _flash_stats_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    from jax.ad_checkpoint import checkpoint_name

    out, lse = _flash_fwd_pallas(
        q, k, v, causal, sm_scale, block_q, block_k, return_lse=True
    )
    # Name the values HERE so the residual vars themselves carry the names:
    # under jax.checkpoint with save_only_these_names("attn_out","attn_lse")
    # the saved copies satisfy both the downstream primal use and the
    # backward's residual needs, and the rematerialized forward's pallas
    # call DCEs away — attention forward runs exactly once per step.
    out = checkpoint_name(out, "attn_out")
    lse = checkpoint_name(lse, "attn_lse")
    return (out, lse), (q, k, v, out, lse)


def _flash_stats_bwd(causal, sm_scale, block_q, block_k, res, g):
    q, k, v, o, lse = res
    g_o, _ = g  # lse cotangent is structurally zero (stats are not a loss path)
    return _flash_bwd_pallas(q, k, v, o, lse, g_o, causal, sm_scale, block_q, block_k)


_flash_stats.defvjp(_flash_stats_fwd, _flash_stats_bwd)


def flash_attention_with_stats(
    q,
    k,
    v,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
):
    """`flash_attention` that also returns the per-row logsumexp.

    Exists for remat integration: the VPU-bound forward kernel is the most
    expensive recompute in a rematerialized transformer block, and saving
    (out, lse) — named inside the vjp forward rule — lets a
    `save_only_these_names` policy skip exactly that rerun
    (models/gpt.py `remat_policy="attn"`).

    The returned lse is STOP-GRADIENTED on every backend: the flash
    backward implements only d(out); declaring lse non-differentiable here
    keeps TPU and the off-TPU reference path consistent instead of silently
    dropping a cotangent on one of them. Use it for logging/remat, not as
    a loss term."""
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if not _on_tpu():
        out = attention_reference(q, k, v, causal, scale)
        *_, S, D = q.shape
        Skv = k.shape[-2]
        logits = jnp.einsum(
            "bhsd,bhtd->bhst", q, k, preferred_element_type=jnp.float32
        ) * scale
        if causal:
            qpos = jnp.arange(S)[:, None] + (Skv - S)
            logits = jnp.where(qpos >= jnp.arange(Skv)[None, :], logits, _NEG_INF)
        B, H = q.shape[0], q.shape[1]
        lse = jax.nn.logsumexp(logits, axis=-1).reshape(B * H, 1, S)
        return out, jax.lax.stop_gradient(lse)
    if block_q is None:
        block_q = 1024
    if block_k is None:
        block_k = 1024
    out, lse = _flash_stats(q, k, v, causal, scale, block_q, block_k)
    return out, jax.lax.stop_gradient(lse)


def flash_attention(
    q,
    k,
    v,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
):
    """Blockwise attention. Pallas on TPU; XLA reference elsewhere.

    Default blocks (1024, 1024) come from the v5e sweeps in
    scripts/bench_flash.py: 60 TFLOP/s fwd+bwd at BOTH 8k and 16k (30.5% of
    the 197 TFLOP/s peak; r5 remeasure — blocks ≥2048 fail to compile), and
    at S=1024 the single-KV-block forward runs 2x faster than block_k=512.
    Note the D=64 head dim caps attention matmuls at ~50% MXU utilization
    (the contraction or output dim is half the 128-wide systolic array), so
    30.5% nominal ≈ 60% of the achievable ceiling."""
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if not _on_tpu():
        return attention_reference(q, k, v, causal, scale)
    if block_q is None:
        block_q = 1024
    if block_k is None:
        block_k = 1024
    return _flash(q, k, v, causal, scale, block_q, block_k)


# ------------------------------------------------------------ ring attention
def _chunk_attn(q, k, v, mask, scale):
    """One K/V chunk's contribution with softmax stats (all fp32)."""
    s = jnp.einsum("bhsd,bhtd->bhst", q, k, preferred_element_type=jnp.float32) * scale
    s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)  # [B,H,S,1]
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bhst,bhtd->bhsd", p, v.astype(jnp.float32))
    return o, m, l


def ring_attention(
    q,
    k,
    v,
    axis: str = "sp",
    causal: bool = True,
    sm_scale: Optional[float] = None,
):
    """Blockwise ring attention over sequence shards (call under shard_map).

    Per device: q,k,v are the LOCAL sequence shard [B, H, S_local, D]. Each of
    the `axis_size` steps attends q against the K/V block currently resident,
    then rotates K/V one hop around the ring (`ppermute` compiles to
    neighbor ICI transfers, overlapped by XLA with the matmuls). Causal
    masking uses global positions, so fully-masked steps contribute nothing.
    """
    # jax.lax.axis_size only exists in newer JAX; psum of a Python constant
    # over a named axis constant-folds to the axis size at trace time, so `n`
    # stays a static int (the scan length and ppermute table need it).
    n = jax.lax.psum(1, axis)
    my = jax.lax.axis_index(axis)
    B, H, S_local, D = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(D)
    qf = q.astype(jnp.float32)

    q_start = my * S_local
    rows = q_start + jnp.arange(S_local)[:, None]  # global q positions

    def step(carry, i):
        acc, m_prev, l_prev, k_cur, v_cur = carry
        src = (my - i) % n  # whose K/V block we hold at step i
        kv_start = src * S_local
        cols = kv_start + jnp.arange(S_local)[None, :]
        mask = (rows >= cols) if causal else jnp.ones((S_local, S_local), bool)
        o_c, m_c, l_c = _chunk_attn(qf, k_cur, v_cur, mask, scale)
        m_new = jnp.maximum(m_prev, m_c)
        alpha = jnp.exp(m_prev - m_new)
        beta = jnp.exp(m_c - m_new)
        acc = acc * alpha + o_c * beta
        l_new = l_prev * alpha + l_c * beta
        perm = [(d, (d + 1) % n) for d in range(n)]
        k_nxt = jax.lax.ppermute(k_cur, axis, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis, perm)
        return (acc, m_new, l_new, k_nxt, v_nxt), None

    init = (
        jnp.zeros((B, H, S_local, D), jnp.float32),
        jnp.full((B, H, S_local, 1), _NEG_INF, jnp.float32),
        jnp.zeros((B, H, S_local, 1), jnp.float32),
        k,
        v,
    )
    (acc, m, l, _, _), _ = jax.lax.scan(step, init, jnp.arange(n))
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ulysses_attention(
    q,
    k,
    v,
    axis: str = "sp",
    causal: bool = True,
    sm_scale: Optional[float] = None,
):
    """DeepSpeed-Ulysses-style context parallelism (call under shard_map).

    Inputs are sequence-sharded [B, H, S_local, D]; `all_to_all` swaps the
    shard axis from sequence to heads, each device runs FULL-sequence
    attention over H/n heads, then swaps back. Requires H % axis_size == 0.
    """
    # [B, H, S/n, D] -> [B, H/n, S, D]
    q2 = jax.lax.all_to_all(q, axis, split_axis=1, concat_axis=2, tiled=True)
    k2 = jax.lax.all_to_all(k, axis, split_axis=1, concat_axis=2, tiled=True)
    v2 = jax.lax.all_to_all(v, axis, split_axis=1, concat_axis=2, tiled=True)
    o2 = flash_attention(q2, k2, v2, causal=causal, sm_scale=sm_scale)
    # [B, H/n, S, D] -> [B, H, S/n, D]
    return jax.lax.all_to_all(o2, axis, split_axis=2, concat_axis=1, tiled=True)
