"""Attention kernels: Pallas flash attention + ring/Ulysses context parallelism.

The reference has NO sequence-parallel attention (SURVEY.md §2.6 — grep shows
long-context entirely delegated to DeepSpeed/FSDP inside Train workers). Here
it is first-class:

  * `flash_attention` — blockwise online-softmax kernel on the MXU
    (Pallas; falls back to an XLA reference off-TPU).
  * `ring_attention`  — sequence shards on the `sp` mesh axis; K/V blocks
    rotate around the ring via `ppermute` with global-position causal
    masking and online-softmax merging. Call under `shard_map`.
  * `ulysses_attention` — all_to_all head<->seq exchange so each device
    runs full-sequence attention on a head subset.

Shapes follow [batch, heads, seq, head_dim] throughout.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


# --------------------------------------------------------------- reference
def attention_reference(q, k, v, causal: bool = True, sm_scale: Optional[float] = None):
    """XLA attention (materializes logits). Ground truth for kernels and the
    off-TPU fallback."""
    *_, S, D = q.shape
    Skv = k.shape[-2]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(D)
    logits = jnp.einsum("bhsd,bhtd->bhst", q, k, preferred_element_type=jnp.float32)
    logits = logits * scale
    if causal:
        qpos = jnp.arange(S)[:, None] + (Skv - S)  # align ends when S != Skv
        kpos = jnp.arange(Skv)[None, :]
        logits = jnp.where(qpos >= kpos, logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhst,bhtd->bhsd", probs.astype(v.dtype), v)


# ------------------------------------------------------------ pallas kernel
def _flash_fwd_kernel(
    q_ref,
    k_ref,
    v_ref,
    o_ref,
    *rest,  # (lse_ref,) when the caller wants softmax stats (training path)
    block_k: int,
    causal: bool,
    sm_scale: float,
    seq_q: int,
    seq_kv: int,
):
    """Inputs are PADDED to block multiples by the caller (pl.ds on a ragged
    tail clamps the start index, silently misaligning data vs mask — so
    padding + masking against the ORIGINAL lengths is the only safe layout).
    seq_q/seq_kv are the original (unpadded) lengths."""
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    block_q = q_ref.shape[1]
    padded_k = k_ref.shape[1]
    # When S != Skv (decode over a cached prefix) queries are END-aligned
    # with keys, matching attention_reference's (Skv - S) offset.
    row_offset = seq_kv - seq_q
    # Keep MXU operands in the input dtype (bf16 runs the MXU at full rate;
    # an f32 upcast here quarters matmul throughput). f32 only for stats.
    q = q_ref[0]  # [Bq, D]

    num_k_blocks = pl.cdiv(padded_k, block_k)
    if causal:
        # Only blocks up to the (offset) diagonal contribute.
        last = jax.lax.div((qi + 1) * block_q + row_offset + block_k - 1, block_k)
        num_k_blocks = jnp.minimum(num_k_blocks, jnp.maximum(last, 1))

    def body(j, carry):
        acc, m_prev, l_prev = carry
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale  # [Bq, Bk] f32
        cols = j * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        valid = cols < seq_kv  # mask the zero-padded tail
        if causal:
            rows = (
                row_offset
                + qi * block_q
                + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            )
            valid = jnp.logical_and(valid, rows >= cols)
        s = jnp.where(valid, s, _NEG_INF)
        m_cur = jnp.max(s, axis=-1, keepdims=True)  # [Bq, 1]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc = acc * alpha + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return acc, m_new, l_new

    D = q_ref.shape[2]
    init = (
        jnp.zeros((block_q, D), jnp.float32),
        jnp.full((block_q, 1), _NEG_INF, jnp.float32),
        jnp.zeros((block_q, 1), jnp.float32),
    )
    acc, m, l = jax.lax.fori_loop(0, num_k_blocks, body, init)
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    if rest:
        # logsumexp per row — the only softmax statistic the backward needs.
        # The lse block is the full (1, 1, S_p) row (TPU tiling forbids a
        # (1, block_q) tile); each qi grid step writes its slice, covering S_p.
        lse_ref = rest[0]
        lse_ref[0, 0, pl.ds(qi * block_q, block_q)] = (
            m + jnp.log(jnp.maximum(l, 1e-30))
        )[:, 0]


def _compiler_params(pltpu, semantics=("parallel", "arbitrary")):
    if hasattr(pltpu, "CompilerParams"):
        return pltpu.CompilerParams(dimension_semantics=semantics)
    return None


def _flash_fwd_pallas(q, k, v, causal: bool, sm_scale: float, block_q: int, block_k: int,
                      interpret: bool = False, return_lse: bool = False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, S, D = q.shape
    Skv = k.shape[2]
    block_q = min(block_q, max(S, 8))
    block_k = min(block_k, Skv)
    # Pad to block multiples (see kernel docstring for why).
    S_p = -(-S // block_q) * block_q
    Skv_p = -(-Skv // block_k) * block_k
    qr = q.reshape(B * H, S, D)
    kr = k.reshape(B * H, Skv, D)
    vr = v.reshape(B * H, Skv, D)
    if S_p != S:
        qr = jnp.pad(qr, ((0, 0), (0, S_p - S), (0, 0)))
    if Skv_p != Skv:
        kr = jnp.pad(kr, ((0, 0), (0, Skv_p - Skv), (0, 0)))
        vr = jnp.pad(vr, ((0, 0), (0, Skv_p - Skv), (0, 0)))
    grid = (B * H, S_p // block_q)
    out_shape = [jax.ShapeDtypeStruct((B * H, S_p, D), q.dtype)]
    out_specs = [pl.BlockSpec((1, block_q, D), lambda bh, i: (bh, i, 0))]
    if return_lse:  # inference forward skips the lse compute+HBM write
        out_shape.append(jax.ShapeDtypeStruct((B * H, 1, S_p), jnp.float32))
        out_specs.append(pl.BlockSpec((1, 1, S_p), lambda bh, i: (bh, 0, 0)))
    res = pl.pallas_call(
        functools.partial(
            _flash_fwd_kernel,
            block_k=block_k,
            causal=causal,
            sm_scale=sm_scale,
            seq_q=S,
            seq_kv=Skv,
        ),
        out_shape=tuple(out_shape),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, Skv_p, D), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, Skv_p, D), lambda bh, i: (bh, 0, 0)),
        ],
        out_specs=tuple(out_specs),
        compiler_params=_compiler_params(pltpu),
        cost_estimate=pl.CostEstimate(
            flops=4 * B * H * S * Skv * D,
            bytes_accessed=2 * (qr.size + kr.size + vr.size) * q.dtype.itemsize,
            transcendentals=B * H * S * Skv,
        ),
        interpret=interpret,
    )(qr, kr, vr)
    out = res[0][:, :S].reshape(B, H, S, D)
    if return_lse:
        return out, res[1]  # lse stays padded/flat — backward consumes it as-is
    return out


def _flash_bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
    *, block_k: int, causal: bool, sm_scale: float, seq_q: int, seq_kv: int,
):
    """dQ for one q block: loop over k blocks up to the causal diagonal.

    FlashAttention-2 backward: P = exp(S - lse); dS = P∘(dO·Vᵀ − Δ);
    dQ = scale · dS·K, with Δ = rowsum(dO∘O) precomputed by the caller.
    """
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    block_q = q_ref.shape[1]
    padded_k = k_ref.shape[1]
    row_offset = seq_kv - seq_q
    q = q_ref[0]    # bf16 — MXU operands stay in input dtype
    do = do_ref[0]
    lse = lse_ref[0, 0][:, None]      # [Bq, 1]
    delta = delta_ref[0, 0][:, None]  # [Bq, 1]

    num_k_blocks = pl.cdiv(padded_k, block_k)
    if causal:
        last = jax.lax.div((qi + 1) * block_q + row_offset + block_k - 1, block_k)
        num_k_blocks = jnp.minimum(num_k_blocks, jnp.maximum(last, 1))

    def body(j, dq):
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :]
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale
        cols = j * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        valid = cols < seq_kv
        if causal:
            rows = (
                row_offset + qi * block_q
                + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            )
            valid = jnp.logical_and(valid, rows >= cols)
        p = jnp.where(valid, jnp.exp(s - lse), 0.0)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = (p * (dp - delta)).astype(k_blk.dtype)
        return dq + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    D = q_ref.shape[2]
    dq = jax.lax.fori_loop(0, num_k_blocks, body, jnp.zeros((block_q, D), jnp.float32))
    dq_ref[0] = (dq * sm_scale).astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    *, block_q: int, causal: bool, sm_scale: float, seq_q: int, seq_kv: int,
):
    """dK/dV for one k block: loop over q blocks from the causal diagonal down.

    dV = Pᵀ·dO ; dK = scale · dSᵀ·Q. Padded q rows contribute nothing because
    dO and Δ are zero-padded there (dS = P∘(0 − 0) = 0)."""
    from jax.experimental import pallas as pl

    ki = pl.program_id(1)
    block_k = k_ref.shape[1]
    padded_q = q_ref.shape[1]
    row_offset = seq_kv - seq_q
    k = k_ref[0]  # bf16 — MXU operands stay in input dtype
    v = v_ref[0]

    num_q_blocks = pl.cdiv(padded_q, block_q)
    start = jnp.int32(0)
    if causal:
        # First q block whose last global row reaches this k block's first col:
        # rows (= row_offset + q_idx) >= ki*block_k  ⇒  q_idx >= ki*block_k - row_offset.
        start = jnp.maximum(
            jax.lax.div(ki * block_k - row_offset, block_q), 0
        ).astype(jnp.int32)

    def body(i, carry):
        dk, dv = carry
        q_blk = q_ref[0, pl.ds(i * block_q, block_q), :]
        do_blk = do_ref[0, pl.ds(i * block_q, block_q), :]
        lse = lse_ref[0, 0, pl.ds(i * block_q, block_q)][:, None]
        delta = delta_ref[0, 0, pl.ds(i * block_q, block_q)][:, None]
        s = jax.lax.dot_general(
            q_blk, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        ) * sm_scale  # [Bq, Bk]
        cols = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        valid = cols < seq_kv
        rows_abs = i * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        # Padded q rows must not reach p: exp against a padded-row lse can
        # overflow to inf, and inf · 0 (zero-padded dO) would make NaNs.
        valid = jnp.logical_and(valid, rows_abs < seq_q)
        if causal:
            valid = jnp.logical_and(valid, rows_abs + row_offset >= cols)
        p = jnp.where(valid, jnp.exp(s - lse), 0.0)
        pb = p.astype(do_blk.dtype)
        dv = dv + jax.lax.dot_general(
            pb, do_blk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do_blk, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = (p * (dp - delta)).astype(q_blk.dtype)
        dk = dk + jax.lax.dot_general(
            ds, q_blk, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return dk, dv

    D = k_ref.shape[2]
    init = (jnp.zeros((block_k, D), jnp.float32), jnp.zeros((block_k, D), jnp.float32))
    dk, dv = jax.lax.fori_loop(start, num_q_blocks, body, init)
    dk_ref[0] = (dk * sm_scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_bwd_pallas(q, k, v, o, lse, g, causal: bool, sm_scale: float,
                      block_q: int, block_k: int, interpret: bool = False):
    """Flash backward: two Pallas passes (dq over q blocks; dk/dv over k
    blocks) against the saved logsumexp — no S×S materialization. Replaces
    the round-1 full-logit XLA fallback (VERDICT.md "What's weak" #1)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, H, S, D = q.shape
    Skv = k.shape[2]
    block_q = min(block_q, max(S, 8))
    block_k = min(block_k, Skv)
    S_p = -(-S // block_q) * block_q
    Skv_p = -(-Skv // block_k) * block_k

    # Δ = rowsum(dO ∘ O) — cheap elementwise, XLA fuses it.
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)

    qr = q.reshape(B * H, S, D)
    kr = k.reshape(B * H, Skv, D)
    vr = v.reshape(B * H, Skv, D)
    gr = g.reshape(B * H, S, D)
    dr = delta.reshape(B * H, 1, S)
    if S_p != S:
        qr = jnp.pad(qr, ((0, 0), (0, S_p - S), (0, 0)))
        gr = jnp.pad(gr, ((0, 0), (0, S_p - S), (0, 0)))
        dr = jnp.pad(dr, ((0, 0), (0, 0), (0, S_p - S)))
    if Skv_p != Skv:
        kr = jnp.pad(kr, ((0, 0), (0, Skv_p - Skv), (0, 0)))
        vr = jnp.pad(vr, ((0, 0), (0, Skv_p - Skv), (0, 0)))
    # lse arrives padded to (BH, 1, S_p) from the forward (same block_q).
    lr = lse

    kwargs = dict(causal=causal, sm_scale=sm_scale, seq_q=S, seq_kv=Skv)
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_k=block_k, **kwargs),
        out_shape=jax.ShapeDtypeStruct((B * H, S_p, D), q.dtype),
        grid=(B * H, S_p // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, Skv_p, D), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, Skv_p, D), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, block_q, D), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, 1, block_q), lambda bh, i: (bh, 0, i)),
            pl.BlockSpec((1, 1, block_q), lambda bh, i: (bh, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda bh, i: (bh, i, 0)),
        compiler_params=_compiler_params(pltpu),
        cost_estimate=pl.CostEstimate(
            flops=6 * B * H * S * Skv * D,
            bytes_accessed=3 * (qr.size + kr.size + vr.size) * q.dtype.itemsize,
            transcendentals=B * H * S * Skv,
        ),
        interpret=interpret,
    )(qr, kr, vr, gr, lr, dr)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, block_q=block_q, **kwargs),
        out_shape=(
            jax.ShapeDtypeStruct((B * H, Skv_p, D), k.dtype),
            jax.ShapeDtypeStruct((B * H, Skv_p, D), v.dtype),
        ),
        grid=(B * H, Skv_p // block_k),
        in_specs=[
            pl.BlockSpec((1, S_p, D), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, S_p, D), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, 1, S_p), lambda bh, i: (bh, 0, 0)),
            pl.BlockSpec((1, 1, S_p), lambda bh, i: (bh, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, block_k, D), lambda bh, i: (bh, i, 0)),
            pl.BlockSpec((1, block_k, D), lambda bh, i: (bh, i, 0)),
        ),
        compiler_params=_compiler_params(pltpu),
        cost_estimate=pl.CostEstimate(
            flops=8 * B * H * S * Skv * D,  # 4 matmuls: s, dv, dp, dk
            bytes_accessed=3 * (qr.size + kr.size + vr.size) * q.dtype.itemsize,
            transcendentals=B * H * S * Skv,
        ),
        interpret=interpret,
    )(qr, kr, vr, gr, lr, dr)

    dq = dq[:, :S].reshape(B, H, S, D)
    dk = dk[:, :Skv].reshape(B, H, Skv, D)
    dv = dv[:, :Skv].reshape(B, H, Skv, D)
    return dq, dk, dv


def _on_tpu() -> bool:
    try:
        return jax.default_backend() in ("tpu", "axon")
    except Exception:  # noqa: BLE001
        return False


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, sm_scale, block_q, block_k):
    return _flash_fwd_pallas(q, k, v, causal, sm_scale, block_q, block_k)


def _flash_fwd(q, k, v, causal, sm_scale, block_q, block_k):
    out, lse = _flash_fwd_pallas(
        q, k, v, causal, sm_scale, block_q, block_k, return_lse=True
    )
    return out, (q, k, v, out, lse)


def _flash_bwd(causal, sm_scale, block_q, block_k, res, g):
    q, k, v, o, lse = res
    return _flash_bwd_pallas(q, k, v, o, lse, g, causal, sm_scale, block_q, block_k)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q,
    k,
    v,
    causal: bool = True,
    sm_scale: Optional[float] = None,
    block_q: int = 512,
    block_k: int = 512,
):
    """Blockwise attention. Pallas on TPU; XLA reference elsewhere."""
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(q.shape[-1])
    if not _on_tpu():
        return attention_reference(q, k, v, causal, scale)
    return _flash(q, k, v, causal, scale, block_q, block_k)


# ------------------------------------------------------------ ring attention
def _chunk_attn(q, k, v, mask, scale):
    """One K/V chunk's contribution with softmax stats (all fp32)."""
    s = jnp.einsum("bhsd,bhtd->bhst", q, k, preferred_element_type=jnp.float32) * scale
    s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)  # [B,H,S,1]
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bhst,bhtd->bhsd", p, v.astype(jnp.float32))
    return o, m, l


def ring_attention(
    q,
    k,
    v,
    axis: str = "sp",
    causal: bool = True,
    sm_scale: Optional[float] = None,
):
    """Blockwise ring attention over sequence shards (call under shard_map).

    Per device: q,k,v are the LOCAL sequence shard [B, H, S_local, D]. Each of
    the `axis_size` steps attends q against the K/V block currently resident,
    then rotates K/V one hop around the ring (`ppermute` compiles to
    neighbor ICI transfers, overlapped by XLA with the matmuls). Causal
    masking uses global positions, so fully-masked steps contribute nothing.
    """
    n = jax.lax.axis_size(axis)
    my = jax.lax.axis_index(axis)
    B, H, S_local, D = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(D)
    qf = q.astype(jnp.float32)

    q_start = my * S_local
    rows = q_start + jnp.arange(S_local)[:, None]  # global q positions

    def step(carry, i):
        acc, m_prev, l_prev, k_cur, v_cur = carry
        src = (my - i) % n  # whose K/V block we hold at step i
        kv_start = src * S_local
        cols = kv_start + jnp.arange(S_local)[None, :]
        mask = (rows >= cols) if causal else jnp.ones((S_local, S_local), bool)
        o_c, m_c, l_c = _chunk_attn(qf, k_cur, v_cur, mask, scale)
        m_new = jnp.maximum(m_prev, m_c)
        alpha = jnp.exp(m_prev - m_new)
        beta = jnp.exp(m_c - m_new)
        acc = acc * alpha + o_c * beta
        l_new = l_prev * alpha + l_c * beta
        perm = [(d, (d + 1) % n) for d in range(n)]
        k_nxt = jax.lax.ppermute(k_cur, axis, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis, perm)
        return (acc, m_new, l_new, k_nxt, v_nxt), None

    init = (
        jnp.zeros((B, H, S_local, D), jnp.float32),
        jnp.full((B, H, S_local, 1), _NEG_INF, jnp.float32),
        jnp.zeros((B, H, S_local, 1), jnp.float32),
        k,
        v,
    )
    (acc, m, l, _, _), _ = jax.lax.scan(step, init, jnp.arange(n))
    return (acc / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ulysses_attention(
    q,
    k,
    v,
    axis: str = "sp",
    causal: bool = True,
    sm_scale: Optional[float] = None,
):
    """DeepSpeed-Ulysses-style context parallelism (call under shard_map).

    Inputs are sequence-sharded [B, H, S_local, D]; `all_to_all` swaps the
    shard axis from sequence to heads, each device runs FULL-sequence
    attention over H/n heads, then swaps back. Requires H % axis_size == 0.
    """
    # [B, H, S/n, D] -> [B, H/n, S, D]
    q2 = jax.lax.all_to_all(q, axis, split_axis=1, concat_axis=2, tiled=True)
    k2 = jax.lax.all_to_all(k, axis, split_axis=1, concat_axis=2, tiled=True)
    v2 = jax.lax.all_to_all(v, axis, split_axis=1, concat_axis=2, tiled=True)
    o2 = flash_attention(q2, k2, v2, causal=causal, sm_scale=sm_scale)
    # [B, H/n, S, D] -> [B, H, S/n, D]
    return jax.lax.all_to_all(o2, axis, split_axis=2, concat_axis=1, tiled=True)
