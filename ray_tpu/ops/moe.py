"""Mixture-of-Experts layer with expert parallelism (absent from the
reference — SURVEY.md §2.6 "Expert parallel (EP/MoE): Absent"; first-class
here per the build plan).

TPU-idiomatic GShard/Switch design: token→expert routing is expressed as
dense one-hot dispatch/combine tensors and einsums — static shapes, no
sorts/gathers, everything lands on the MXU, and under pjit the expert axis
of the weights shards over the `ep` mesh axis (XLA inserts the all-to-alls).

Top-1 (Switch) and top-2 (GShard) gating with capacity dropping and the
standard load-balancing auxiliary loss.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import jax
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class MoEConfig:
    num_experts: int = 8
    top_k: int = 2                  # 1 = Switch, 2 = GShard
    capacity_factor: float = 1.25
    d_model: int = 768
    d_ff: int = 3072
    aux_loss_weight: float = 0.01
    activation: str = "gelu"        # gelu | swiglu (adds w_gate per expert)
    dtype: object = jnp.bfloat16

    def capacity(self, num_tokens: int) -> int:
        c = int(self.capacity_factor * num_tokens * self.top_k / self.num_experts)
        return max(c, 4)


def moe_init(rng, cfg: MoEConfig) -> Dict[str, jnp.ndarray]:
    """Params with logical dims:
    w_router (embed, experts); w_in (experts, embed, mlp); w_out (experts, mlp, embed).
    """
    k1, k2, k3, k4 = jax.random.split(rng, 4)
    E, D, F = cfg.num_experts, cfg.d_model, cfg.d_ff
    s = 0.02
    params = {
        "w_router": jax.random.normal(k1, (D, E), jnp.float32) * s,
        "w_in": jax.random.normal(k2, (E, D, F), jnp.float32) * s,
        "w_out": jax.random.normal(k3, (E, F, D), jnp.float32) * s,
    }
    if cfg.activation == "swiglu":
        params["w_gate"] = jax.random.normal(k4, (E, D, F), jnp.float32) * s
    return params


def _one_hot_dispatch(gate_idx, probs, mask, capacity, num_experts):
    """Build dispatch/combine slices for one routing choice.

    gate_idx [N] expert per token; mask [N] tokens still in play;
    returns (dispatch [N, E, C] one-hot, gate_probs [N] prob of this choice,
    kept [N] capacity mask).
    """
    expert_mask = jax.nn.one_hot(gate_idx, num_experts, dtype=jnp.float32) * mask[:, None]
    # Position of each token within its expert's buffer (cumulative count).
    position = jnp.cumsum(expert_mask, axis=0) * expert_mask  # [N, E]
    position = position.sum(axis=-1) - 1.0                    # [N], -1 if masked
    kept = (position >= 0) & (position < capacity)
    pos_oh = jax.nn.one_hot(position.astype(jnp.int32), capacity, dtype=jnp.float32)
    dispatch = expert_mask[:, :, None] * pos_oh[:, None, :] * kept[:, None, None]
    gate_probs = (probs * expert_mask).sum(axis=-1)
    return dispatch, gate_probs, kept


def moe_router(x_flat, w_router, cfg: MoEConfig) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """x_flat [N, D] → (combine [N, E, C], aux_loss scalar).

    combine holds the gating weight of each (token, expert, slot); dispatch
    is its boolean support.
    """
    N = x_flat.shape[0]
    E = cfg.num_experts
    C = cfg.capacity(N)
    logits = x_flat.astype(jnp.float32) @ w_router  # router math in f32
    probs = jax.nn.softmax(logits, axis=-1)        # [N, E]

    gate1 = jnp.argmax(probs, axis=-1)
    disp1, p1, kept1 = _one_hot_dispatch(
        gate1, probs, jnp.ones(N, jnp.float32), C, E
    )

    # Load-balancing aux loss (Switch eq. 4): E * Σ_e f_e · P_e
    me = jax.nn.one_hot(gate1, E, dtype=jnp.float32).mean(axis=0)  # token fraction
    pe = probs.mean(axis=0)                                        # mean router prob
    aux = E * jnp.sum(me * pe)

    if cfg.top_k == 1:
        combine = disp1 * p1[:, None, None]
        return combine, aux

    # Top-2: mask out the first choice, route the remainder.
    probs2 = probs * (1.0 - jax.nn.one_hot(gate1, E, dtype=jnp.float32))
    gate2 = jnp.argmax(probs2, axis=-1)
    # Second-choice buffer positions start after all first-choice tokens.
    first_counts = jax.nn.one_hot(gate1, E, dtype=jnp.float32).sum(axis=0)  # [E]
    expert_mask2 = jax.nn.one_hot(gate2, E, dtype=jnp.float32)
    position2 = jnp.cumsum(expert_mask2, axis=0) * expert_mask2
    position2 = (position2 + first_counts[None, :] * expert_mask2).sum(axis=-1) - 1.0
    kept2 = (position2 >= 0) & (position2 < C)
    pos2_oh = jax.nn.one_hot(position2.astype(jnp.int32), C, dtype=jnp.float32)
    disp2 = expert_mask2[:, :, None] * pos2_oh[:, None, :] * kept2[:, None, None]
    p2 = (probs * expert_mask2).sum(axis=-1)

    # Renormalize the two gate probs over the kept choices.
    denom = p1 * kept1 + p2 * kept2
    denom = jnp.maximum(denom, 1e-9)
    combine = disp1 * (p1 * kept1 / denom)[:, None, None] + disp2 * (
        p2 * kept2 / denom
    )[:, None, None]
    return combine, aux


def moe_forward(params, x, cfg: MoEConfig) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """x [..., D] → (y [..., D], aux_loss). Shard w_in/w_out on `ep` via
    logical dim "experts"; the dispatch einsum's [E, C, D] intermediate then
    shards on ep and XLA places the token all-to-alls on ICI."""
    orig_shape = x.shape
    D = orig_shape[-1]
    x_flat = x.reshape(-1, D)

    combine, aux = moe_router(x_flat, params["w_router"], cfg)
    combine = combine.astype(cfg.dtype)
    dispatch = (combine > 0).astype(cfg.dtype)

    xc = x_flat.astype(cfg.dtype)
    expert_in = jnp.einsum("nec,nd->ecd", dispatch, xc)         # [E, C, D]
    h = jnp.einsum("ecd,edf->ecf", expert_in, params["w_in"].astype(cfg.dtype))
    if cfg.activation == "swiglu":
        g = jnp.einsum("ecd,edf->ecf", expert_in, params["w_gate"].astype(cfg.dtype))
        h = jax.nn.silu(g) * h
    else:
        h = jax.nn.gelu(h)
    expert_out = jnp.einsum("ecf,efd->ecd", h, params["w_out"].astype(cfg.dtype))
    y = jnp.einsum("nec,ecd->nd", combine, expert_out)          # [N, D]
    return y.reshape(orig_shape), cfg.aux_loss_weight * aux


MOE_LOGICAL_DIMS = {
    "w_router": ("embed", "experts"),
    "w_in": ("experts", "embed", "mlp"),
    "w_out": ("experts", "mlp", "embed"),
    "w_gate": ("experts", "embed", "mlp"),
}
