"""Workflow events (reference: `python/ray/workflow/event_listener.py`).

`wait_for_event(Listener, *args)` produces a DAG node whose step blocks
until the listener observes its event; the observed payload is checkpointed
like any step result, so resumed workflows do not re-wait for events that
already fired.
"""

from __future__ import annotations

import time


class EventListener:
    """Subclass and implement `poll_for_event` (blocking; return the event
    payload). Runs inside a task, so it may poll external systems freely."""

    def poll_for_event(self, *args, **kwargs):
        raise NotImplementedError


class TimerListener(EventListener):
    """Fires after `seconds` (reference: `workflow.sleep`)."""

    def poll_for_event(self, seconds: float):
        time.sleep(float(seconds))
        return time.time()


def wait_for_event(listener_cls, *args, **kwargs):
    """Bind a step that resolves when the listener's event fires."""
    from ..core.api import remote

    if not (isinstance(listener_cls, type) and issubclass(listener_cls, EventListener)):
        raise TypeError("wait_for_event expects an EventListener subclass")

    @remote
    def _wait_for_event(*a, **kw):
        return listener_cls().poll_for_event(*a, **kw)

    _wait_for_event.__name__ = f"wait_{listener_cls.__name__}"
    return _wait_for_event.bind(*args, **kwargs)
