"""Workflow — durable DAG execution with storage-backed resume.

Reference analog: `python/ray/workflow` (`workflow_executor.py`,
`workflow_state.py`, `workflow_storage.py`, `api.py`): build a DAG with
`.bind()`, run it with `workflow.run(dag, workflow_id=...)`; every step's
result is durably checkpointed, so a crashed/failed workflow resumes from
the last completed step with `workflow.resume(workflow_id)`.

Redesign notes (TPU-first): steps are ordinary tasks on the cluster; the
executor walks the DAG in-process and checkpoints to a filesystem root
(point it at NFS/GCS-fuse for multi-host durability). Deterministic
structural step keys replace the reference's workflow-step registry.

Usage:
    from ray_tpu import workflow

    @ray_tpu.remote
    def add(a, b):
        return a + b

    dag = add.bind(add.bind(1, 2), 3)
    assert workflow.run(dag, workflow_id="sum3") == 6
    workflow.get_status("sum3")  # "SUCCESSFUL"
"""

from __future__ import annotations

import threading
import uuid
from concurrent.futures import Future
from typing import Any, Dict, List, Optional, Tuple

import cloudpickle

from ..dag import DAGNode
from .executor import (
    CANCELED,
    FAILED,
    RESUMABLE,
    RUNNING,
    SUCCESSFUL,
    WorkflowCancellationError,
    WorkflowExecutor,
)
from .event_listener import EventListener, TimerListener, wait_for_event
from .storage import WorkflowStorage, default_root

__all__ = [
    "EventListener",
    "TimerListener",
    "wait_for_event",
    "init",
    "run",
    "run_async",
    "resume",
    "resume_async",
    "resume_all",
    "get_status",
    "get_output",
    "get_metadata",
    "list_all",
    "cancel",
    "delete",
    "continuation",
    "with_options",
    "WorkflowStatus",
]


class WorkflowStatus:
    RUNNING = RUNNING
    SUCCESSFUL = SUCCESSFUL
    FAILED = FAILED
    CANCELED = CANCELED
    RESUMABLE = RESUMABLE


_storage: Optional[WorkflowStorage] = None
_lock = threading.Lock()
# Workflow ids executing in THIS process — closes the submit→RUNNING race a
# status file alone cannot (two quick run_async calls before the first
# executor sets its status).
_running_local: set = set()


def init(storage: Optional[str] = None):
    """Set the durable storage root (default: $RAY_TPU_WORKFLOW_STORAGE or
    /tmp/ray_tpu/workflows)."""
    global _storage
    with _lock:
        _storage = WorkflowStorage(storage)


def _get_storage() -> WorkflowStorage:
    global _storage
    with _lock:
        if _storage is None:
            _storage = WorkflowStorage(default_root())
        return _storage


# ------------------------------------------------------------------- running
def run(
    dag: DAGNode,
    *,
    workflow_id: Optional[str] = None,
    metadata: Optional[dict] = None,
) -> Any:
    """Run a DAG durably to completion; returns its output."""
    return run_async(dag, workflow_id=workflow_id, metadata=metadata).result()


def run_async(
    dag: DAGNode,
    *,
    workflow_id: Optional[str] = None,
    metadata: Optional[dict] = None,
) -> Future:
    storage = _get_storage()
    workflow_id = workflow_id or f"workflow-{uuid.uuid4().hex[:12]}"
    with _lock:
        already_local = workflow_id in _running_local
    if storage.exists(workflow_id):
        status = storage.get_status(workflow_id)
        if status == SUCCESSFUL:
            # Idempotent re-run of a finished workflow returns its output.
            fut: Future = Future()
            fut.set_result(storage.load_output(workflow_id))
            return fut
        if status == RUNNING or already_local:
            raise RuntimeError(
                f"workflow '{workflow_id}' is already running; use resume() "
                "after a crash or wait for it to finish"
            )
        # FAILED/CANCELED/RESUMABLE: fall through — re-running resumes from
        # checkpoints (cancel marker cleared).
        storage.clear_cancel(workflow_id)
    else:
        storage.create(workflow_id, cloudpickle.dumps(dag), metadata or {})
    return _spawn(storage, workflow_id, dag)


def _spawn(storage: WorkflowStorage, workflow_id: str, dag: DAGNode) -> Future:
    # Claim RUNNING synchronously — before the executor thread exists — so a
    # concurrent run_async for the same id cannot start a duplicate executor.
    with _lock:
        if workflow_id in _running_local:
            raise RuntimeError(f"workflow '{workflow_id}' is already running")
        _running_local.add(workflow_id)
    storage.set_status(workflow_id, RUNNING)
    fut: Future = Future()
    executor = WorkflowExecutor(storage, workflow_id)

    def go():
        try:
            fut.set_result(executor.run(dag))
        except BaseException as e:  # noqa: BLE001
            fut.set_exception(e)
        finally:
            with _lock:
                _running_local.discard(workflow_id)

    t = threading.Thread(target=go, daemon=True, name=f"workflow-{workflow_id}")
    t.start()
    return fut


# ------------------------------------------------------------------ resuming
def resume(workflow_id: str) -> Any:
    return resume_async(workflow_id).result()


def resume_async(workflow_id: str) -> Future:
    storage = _get_storage()
    if not storage.exists(workflow_id):
        raise ValueError(f"no such workflow: {workflow_id}")
    if storage.get_status(workflow_id) == SUCCESSFUL:
        fut: Future = Future()
        fut.set_result(storage.load_output(workflow_id))
        return fut
    storage.clear_cancel(workflow_id)
    dag = storage.load_dag(workflow_id)
    return _spawn(storage, workflow_id, dag)


def resume_all() -> List[Tuple[str, Future]]:
    """Resume every workflow that did not finish (reference:
    `workflow.resume_all` after cluster restart)."""
    storage = _get_storage()
    out = []
    for wid, status in storage.list_all():
        if status in (RUNNING, FAILED, RESUMABLE):
            out.append((wid, resume_async(wid)))
    return out


# ----------------------------------------------------------------- inspection
def get_status(workflow_id: str) -> Optional[str]:
    return _get_storage().get_status(workflow_id)


def get_output(workflow_id: str) -> Any:
    storage = _get_storage()
    if not storage.has_output(workflow_id):
        status = storage.get_status(workflow_id)
        raise ValueError(f"workflow '{workflow_id}' has no output (status={status})")
    return storage.load_output(workflow_id)


def get_metadata(workflow_id: str) -> dict:
    meta = _get_storage().get_metadata(workflow_id)
    meta["status"] = get_status(workflow_id)
    return meta


def list_all(status_filter: Optional[str] = None) -> List[Tuple[str, Optional[str]]]:
    all_wfs = _get_storage().list_all()
    if status_filter is None:
        return all_wfs
    return [(w, s) for w, s in all_wfs if s == status_filter]


# ----------------------------------------------------------------- mutation
def cancel(workflow_id: str):
    """Request cancellation; takes effect at the next step boundary."""
    storage = _get_storage()
    if not storage.exists(workflow_id):
        raise ValueError(f"no such workflow: {workflow_id}")
    storage.mark_cancel(workflow_id)


def delete(workflow_id: str):
    _get_storage().delete(workflow_id)


# ------------------------------------------------------------------- helpers
def continuation(dag: DAGNode) -> DAGNode:
    """Mark a step's return value as a continuation DAG (reference:
    `workflow.continuation`) — the executor keeps walking it durably."""
    return dag


def with_options(node: DAGNode, **options) -> DAGNode:
    """Attach per-step options: max_retries (int), checkpoint (bool),
    catch_exceptions (bool) — reference analog: `workflow.options()`."""
    node._workflow_options = dict(options)
    return node
