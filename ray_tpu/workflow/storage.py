"""Durable workflow storage.

Reference analog: `python/ray/workflow/workflow_storage.py` — step results,
DAG, and status durably recorded so a crashed workflow resumes from its last
checkpoint. Filesystem-backed (one directory per workflow); the root can live
on NFS/GCS-fuse for cross-host durability.

Layout:
    <root>/<workflow_id>/dag.pkl            the bound DAG (cloudpickle)
    <root>/<workflow_id>/status             text status marker
    <root>/<workflow_id>/meta.json          creation time, user metadata
    <root>/<workflow_id>/cancel             cancel marker (presence = cancel)
    <root>/<workflow_id>/steps/<key>.pkl    checkpointed step results
    <root>/<workflow_id>/output.pkl         final workflow output
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, List, Optional, Tuple

import cloudpickle

_DEFAULT_ROOT = "/tmp/ray_tpu/workflows"


def default_root() -> str:
    return os.environ.get("RAY_TPU_WORKFLOW_STORAGE", _DEFAULT_ROOT)


def _atomic_write(path: str, data: bytes):
    import threading
    import uuid

    tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}.{uuid.uuid4().hex[:6]}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class WorkflowStorage:
    def __init__(self, root: Optional[str] = None):
        self.root = root or default_root()
        os.makedirs(self.root, exist_ok=True)

    # ------------------------------------------------------------- layout
    def _wf_dir(self, workflow_id: str) -> str:
        return os.path.join(self.root, workflow_id)

    def _steps_dir(self, workflow_id: str) -> str:
        return os.path.join(self._wf_dir(workflow_id), "steps")

    def exists(self, workflow_id: str) -> bool:
        return os.path.isdir(self._wf_dir(workflow_id))

    # ------------------------------------------------------------ lifecycle
    def create(self, workflow_id: str, dag_bytes: bytes, metadata: dict):
        os.makedirs(self._steps_dir(workflow_id), exist_ok=True)
        _atomic_write(os.path.join(self._wf_dir(workflow_id), "dag.pkl"), dag_bytes)
        meta = {"created_at": time.time(), **metadata}
        _atomic_write(
            os.path.join(self._wf_dir(workflow_id), "meta.json"),
            json.dumps(meta).encode(),
        )

    def load_dag(self, workflow_id: str) -> Any:
        with open(os.path.join(self._wf_dir(workflow_id), "dag.pkl"), "rb") as f:
            return cloudpickle.loads(f.read())

    def set_status(self, workflow_id: str, status: str):
        _atomic_write(
            os.path.join(self._wf_dir(workflow_id), "status"), status.encode()
        )

    def get_status(self, workflow_id: str) -> Optional[str]:
        try:
            with open(os.path.join(self._wf_dir(workflow_id), "status"), "rb") as f:
                return f.read().decode()
        except OSError:
            return None

    def get_metadata(self, workflow_id: str) -> dict:
        try:
            with open(os.path.join(self._wf_dir(workflow_id), "meta.json")) as f:
                return json.load(f)
        except OSError:
            return {}

    def list_all(self) -> List[Tuple[str, Optional[str]]]:
        out = []
        for name in sorted(os.listdir(self.root)):
            if os.path.isdir(self._wf_dir(name)):
                out.append((name, self.get_status(name)))
        return out

    def delete(self, workflow_id: str):
        import shutil

        shutil.rmtree(self._wf_dir(workflow_id), ignore_errors=True)

    # -------------------------------------------------------------- cancel
    def mark_cancel(self, workflow_id: str):
        _atomic_write(os.path.join(self._wf_dir(workflow_id), "cancel"), b"1")

    def cancel_requested(self, workflow_id: str) -> bool:
        return os.path.exists(os.path.join(self._wf_dir(workflow_id), "cancel"))

    def clear_cancel(self, workflow_id: str):
        try:
            os.remove(os.path.join(self._wf_dir(workflow_id), "cancel"))
        except OSError:
            pass

    # --------------------------------------------------------------- steps
    def _step_path(self, workflow_id: str, key: str) -> str:
        return os.path.join(self._steps_dir(workflow_id), f"{key}.pkl")

    def has_step(self, workflow_id: str, key: str) -> bool:
        return os.path.exists(self._step_path(workflow_id, key))

    def save_step(self, workflow_id: str, key: str, value: Any):
        _atomic_write(
            self._step_path(workflow_id, key), cloudpickle.dumps(value)
        )

    def load_step(self, workflow_id: str, key: str) -> Any:
        with open(self._step_path(workflow_id, key), "rb") as f:
            return cloudpickle.loads(f.read())

    # --------------------------------------------------------------- output
    def save_output(self, workflow_id: str, value: Any):
        _atomic_write(
            os.path.join(self._wf_dir(workflow_id), "output.pkl"),
            cloudpickle.dumps(value),
        )

    def load_output(self, workflow_id: str) -> Any:
        with open(os.path.join(self._wf_dir(workflow_id), "output.pkl"), "rb") as f:
            return cloudpickle.loads(f.read())

    def has_output(self, workflow_id: str) -> bool:
        return os.path.exists(os.path.join(self._wf_dir(workflow_id), "output.pkl"))
