"""Workflow executor — checkpointed DAG walking with retries/continuations.

Reference analog: `python/ray/workflow/workflow_executor.py` +
`workflow_state_from_dag.py`: each step runs as a task, its result is durably
checkpointed, and resume replays only the steps without checkpoints.

Step identity: nodes get deterministic keys from a structural DFS of the
bound DAG (same DAG → same keys across processes/pickling), so resume after
a crash matches checkpoints to steps without a registry.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ..dag import DAGNode, FunctionNode, InputNode, MultiOutputNode
from .storage import WorkflowStorage

# Workflow statuses (reference: `python/ray/workflow/common.py` WorkflowStatus).
RUNNING = "RUNNING"
SUCCESSFUL = "SUCCESSFUL"
FAILED = "FAILED"
CANCELED = "CANCELED"
RESUMABLE = "RESUMABLE"


class WorkflowCancellationError(Exception):
    pass


def _step_name(node: DAGNode) -> str:
    fn = getattr(node, "_remote_fn", None)
    return getattr(fn, "__name__", None) or type(node).__name__.lower()


def assign_step_keys(root: DAGNode, prefix: str = "") -> Dict[int, str]:
    """Deterministic structural DFS over bound args — stable across pickle
    round-trips, which is what makes crash-resume line up with checkpoints."""
    keys: Dict[int, str] = {}
    counter = [0]

    def visit(node):
        if not isinstance(node, DAGNode) or id(node) in keys:
            return
        for a in node._bound_args:
            visit(a)
        for v in node._bound_kwargs.values():
            visit(v)
        if isinstance(node, MultiOutputNode):
            for o in node._outputs:
                visit(o)
        idx = counter[0]
        counter[0] += 1
        keys[id(node)] = f"{prefix}{idx}_{_step_name(node)}"

    visit(root)
    return keys


class WorkflowExecutor:
    def __init__(self, storage: WorkflowStorage, workflow_id: str):
        self.storage = storage
        self.workflow_id = workflow_id
        self._cache_lock = threading.Lock()

    # ------------------------------------------------------------ execution
    def run(self, dag: DAGNode, input_value=None) -> Any:
        """Execute to completion (or raise); returns the final output.
        The caller (run_async) has already marked the workflow RUNNING."""
        try:
            out = self._exec_subdag(dag, input_value, prefix="")
            self.storage.save_output(self.workflow_id, out)
            self.storage.set_status(self.workflow_id, SUCCESSFUL)
            return out
        except WorkflowCancellationError:
            self.storage.set_status(self.workflow_id, CANCELED)
            raise
        except BaseException:
            self.storage.set_status(self.workflow_id, FAILED)
            raise

    def _exec_subdag(self, root: DAGNode, input_value, prefix: str) -> Any:
        keys = assign_step_keys(root, prefix)
        cache: Dict[int, Any] = {}
        return self._exec_node(root, keys, cache, input_value)

    def _exec_many(self, nodes, keys, cache, input_value) -> List[Any]:
        """Evaluate sibling subtrees concurrently — independent DAG branches
        run in parallel on the cluster instead of serializing on the
        driver's blocking get (one thread per extra branch; DAG widths are
        small)."""
        dag_children = [n for n in nodes if isinstance(n, DAGNode)]
        if len(dag_children) > 1:
            results: Dict[int, Any] = {}
            errors: List[BaseException] = []

            def work(i, n):
                try:
                    results[i] = self._exec_node(n, keys, cache, input_value)
                except BaseException as e:  # noqa: BLE001
                    errors.append(e)

            threads = [
                threading.Thread(target=work, args=(i, n), daemon=True)
                for i, n in enumerate(nodes)
                if isinstance(n, DAGNode)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            if errors:
                raise errors[0]
            return [
                results[i] if isinstance(n, DAGNode) else n
                for i, n in enumerate(nodes)
            ]
        return [self._exec_node(n, keys, cache, input_value) for n in nodes]

    def _exec_node(self, node, keys, cache, input_value) -> Any:
        if not isinstance(node, DAGNode):
            return node
        # Memoized single execution per node, safe under branch threads:
        # the first visitor claims the slot; later visitors wait on its event.
        with self._cache_lock:
            slot = cache.get(id(node))
            owner = slot is None
            if owner:
                slot = cache[id(node)] = {"event": threading.Event()}
        if not owner:
            slot["event"].wait()
            if "error" in slot:
                raise slot["error"]
            return slot["value"]
        try:
            val = self._compute_node(node, keys, cache, input_value)
            slot["value"] = val
            return val
        except BaseException as e:  # noqa: BLE001
            slot["error"] = e
            raise
        finally:
            slot["event"].set()

    def _compute_node(self, node, keys, cache, input_value) -> Any:
        if isinstance(node, InputNode):
            return input_value
        if isinstance(node, MultiOutputNode):
            return self._exec_many(node._outputs, keys, cache, input_value)

        key = keys[id(node)]
        if self.storage.has_step(self.workflow_id, key):
            return self._resolve_continuations(
                self.storage.load_step(self.workflow_id, key), key, input_value
            )

        if self.storage.cancel_requested(self.workflow_id):
            raise WorkflowCancellationError(self.workflow_id)

        bound = list(node._bound_args) + list(node._bound_kwargs.values())
        vals = self._exec_many(bound, keys, cache, input_value)
        args = vals[: len(node._bound_args)]
        kwargs = dict(zip(node._bound_kwargs.keys(), vals[len(node._bound_args):]))
        val = self._run_step(node, key, args, kwargs)
        opts = getattr(node, "_workflow_options", None) or {}
        if opts.get("checkpoint", True):
            self.storage.save_step(self.workflow_id, key, val)
        return self._resolve_continuations(val, key, input_value)

    def _resolve_continuations(self, val, key: str, input_value) -> Any:
        """A step (root or nested) may return another DAG — keep walking it
        durably under a key-prefixed namespace (reference:
        `workflow.continuation`)."""
        depth = 0
        while isinstance(val, DAGNode):
            depth += 1
            val = self._exec_subdag(val, input_value, prefix=f"{key}.c{depth}.")
        return val

    def _run_step(self, node, key: str, args: List, kwargs: dict) -> Any:
        """One step = one task submission, retried per step options
        (reference: per-step `max_retries` in `workflow/common.py`)."""
        from ..core import api

        opts = getattr(node, "_workflow_options", None) or {}
        max_retries = int(opts.get("max_retries", 0))
        catch = bool(opts.get("catch_exceptions", False))
        if not isinstance(node, FunctionNode):
            raise TypeError(
                f"workflow steps must be function nodes, got {type(node).__name__} "
                "(actor nodes are not durable — reference workflow has the "
                "same task-only restriction for checkpointed steps)"
            )
        attempt = 0
        while True:
            if self.storage.cancel_requested(self.workflow_id):
                raise WorkflowCancellationError(self.workflow_id)
            try:
                val = api.get(node._remote_fn.remote(*args, **kwargs))
                return (val, None) if catch else val
            except WorkflowCancellationError:
                raise
            except Exception as e:  # noqa: BLE001
                if attempt < max_retries:
                    attempt += 1
                    time.sleep(min(0.2 * attempt, 2.0))
                    continue
                if catch:
                    return (None, e)
                raise
