"""Workflow executor — checkpointed DAG walking with retries/continuations.

Reference analog: `python/ray/workflow/workflow_executor.py` +
`workflow_state_from_dag.py`: each step runs as a task, its result is durably
checkpointed, and resume replays only the steps without checkpoints.

Step identity: nodes get deterministic keys from a structural DFS of the
bound DAG (same DAG → same keys across processes/pickling), so resume after
a crash matches checkpoints to steps without a registry.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from ..dag import DAGNode, FunctionNode, InputNode, MultiOutputNode
from .storage import WorkflowStorage

# Workflow statuses (reference: `python/ray/workflow/common.py` WorkflowStatus).
RUNNING = "RUNNING"
SUCCESSFUL = "SUCCESSFUL"
FAILED = "FAILED"
CANCELED = "CANCELED"
RESUMABLE = "RESUMABLE"


class WorkflowCancellationError(Exception):
    pass


def _step_name(node: DAGNode) -> str:
    fn = getattr(node, "_remote_fn", None)
    return getattr(fn, "__name__", None) or type(node).__name__.lower()


def assign_step_keys(root: DAGNode, prefix: str = "") -> Dict[int, str]:
    """Deterministic structural DFS over bound args — stable across pickle
    round-trips, which is what makes crash-resume line up with checkpoints."""
    keys: Dict[int, str] = {}
    counter = [0]

    def visit(node):
        if not isinstance(node, DAGNode) or id(node) in keys:
            return
        for a in node._bound_args:
            visit(a)
        for v in node._bound_kwargs.values():
            visit(v)
        if isinstance(node, MultiOutputNode):
            for o in node._outputs:
                visit(o)
        idx = counter[0]
        counter[0] += 1
        keys[id(node)] = f"{prefix}{idx}_{_step_name(node)}"

    visit(root)
    return keys


class WorkflowExecutor:
    def __init__(self, storage: WorkflowStorage, workflow_id: str):
        self.storage = storage
        self.workflow_id = workflow_id

    # ------------------------------------------------------------ execution
    def run(self, dag: DAGNode, input_value=None) -> Any:
        """Execute to completion (or raise); returns the final output."""
        self.storage.set_status(self.workflow_id, RUNNING)
        try:
            out = self._exec_subdag(dag, input_value, prefix="")
            # Continuations: a step may return another DAG to keep going
            # (reference: `workflow.continuation`).
            depth = 0
            while isinstance(out, DAGNode):
                depth += 1
                out = self._exec_subdag(out, input_value, prefix=f"c{depth}.")
            self.storage.save_output(self.workflow_id, out)
            self.storage.set_status(self.workflow_id, SUCCESSFUL)
            return out
        except WorkflowCancellationError:
            self.storage.set_status(self.workflow_id, CANCELED)
            raise
        except BaseException:
            self.storage.set_status(self.workflow_id, FAILED)
            raise

    def _exec_subdag(self, root: DAGNode, input_value, prefix: str) -> Any:
        keys = assign_step_keys(root, prefix)
        cache: Dict[int, Any] = {}
        return self._exec_node(root, keys, cache, input_value)

    def _exec_node(self, node, keys, cache, input_value) -> Any:
        if not isinstance(node, DAGNode):
            return node
        if id(node) in cache:
            return cache[id(node)]
        if isinstance(node, InputNode):
            cache[id(node)] = input_value
            return input_value
        if isinstance(node, MultiOutputNode):
            val = [
                self._exec_node(o, keys, cache, input_value) for o in node._outputs
            ]
            cache[id(node)] = val
            return val

        key = keys[id(node)]
        if self.storage.has_step(self.workflow_id, key):
            val = self.storage.load_step(self.workflow_id, key)
            cache[id(node)] = val
            return val

        if self.storage.cancel_requested(self.workflow_id):
            raise WorkflowCancellationError(self.workflow_id)

        args = [self._exec_node(a, keys, cache, input_value) for a in node._bound_args]
        kwargs = {
            k: self._exec_node(v, keys, cache, input_value)
            for k, v in node._bound_kwargs.items()
        }
        val = self._run_step(node, key, args, kwargs)
        opts = getattr(node, "_workflow_options", None) or {}
        if opts.get("checkpoint", True):
            self.storage.save_step(self.workflow_id, key, val)
        cache[id(node)] = val
        return val

    def _run_step(self, node, key: str, args: List, kwargs: dict) -> Any:
        """One step = one task submission, retried per step options
        (reference: per-step `max_retries` in `workflow/common.py`)."""
        from ..core import api

        opts = getattr(node, "_workflow_options", None) or {}
        max_retries = int(opts.get("max_retries", 0))
        catch = bool(opts.get("catch_exceptions", False))
        if not isinstance(node, FunctionNode):
            raise TypeError(
                f"workflow steps must be function nodes, got {type(node).__name__} "
                "(actor nodes are not durable — reference workflow has the "
                "same task-only restriction for checkpointed steps)"
            )
        attempt = 0
        while True:
            if self.storage.cancel_requested(self.workflow_id):
                raise WorkflowCancellationError(self.workflow_id)
            try:
                val = api.get(node._remote_fn.remote(*args, **kwargs))
                return (val, None) if catch else val
            except WorkflowCancellationError:
                raise
            except Exception as e:  # noqa: BLE001
                if attempt < max_retries:
                    attempt += 1
                    time.sleep(min(0.2 * attempt, 2.0))
                    continue
                if catch:
                    return (None, e)
                raise
