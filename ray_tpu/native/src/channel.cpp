// Native seqlock channel ops — the hot path of compiled-DAG / pipeline
// edges (reference analog: the mmap'd plasma channels behind
// `python/ray/experimental/channel.py:49,99,135`, whose buffer reuse +
// busy-wait loops live in C++ inside plasma).
//
// Operates IN PLACE on the shm segment the Python `Channel` owns — header
// layout is shared with the pure-Python fallback (experimental/channel.py):
//   [0]        u64 seq     (publish counter; release-stored)
//   [8]        u64 length
//   [16]       u64 flag    (0 normal, 1 stop)
//   [24 + 8k]  u64 ack_k   (reader k's last consumed seq)
//
// Correctness over the Python version: real acquire/release atomics instead
// of GIL-incidental ordering; latency: adaptive spin→yield→sleep instead of
// a fixed 500µs poll.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <ctime>

#include <sched.h>

namespace {

inline std::atomic<uint64_t>* slot(uint8_t* base, uint64_t off) {
    return reinterpret_cast<std::atomic<uint64_t>*>(base + off);
}

inline uint64_t now_us() {
    timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return static_cast<uint64_t>(ts.tv_sec) * 1000000ull + ts.tv_nsec / 1000;
}

inline void cpu_pause() {
#if defined(__x86_64__) || defined(__i386__)
    __builtin_ia32_pause();
#else
    std::atomic_thread_fence(std::memory_order_seq_cst);
#endif
}

// Adaptive waiter: ~20µs of pause-spins, then yields, then 100µs sleeps.
struct Waiter {
    uint64_t spins = 0;
    void wait() {
        if (spins < 2000) {
            cpu_pause();
        } else if (spins < 2200) {
            sched_yield();
        } else {
            timespec ts{0, 100000};  // 100µs
            nanosleep(&ts, nullptr);
        }
        ++spins;
    }
};

}  // namespace

extern "C" {

// Block until every reader acked the previous message, then copy the payload
// and publish. Returns 0 ok, -1 timeout. timeout_us < 0 = infinite.
int64_t rtpu_ch_write(uint8_t* base, uint64_t num_readers,
                      const uint8_t* data, uint64_t len, uint64_t flag,
                      int64_t timeout_us) {
    const uint64_t header = 24 + 8 * num_readers;
    auto* seq_slot = slot(base, 0);
    const uint64_t seq = seq_slot->load(std::memory_order_relaxed);
    const uint64_t deadline = timeout_us < 0 ? 0 : now_us() + timeout_us;
    Waiter w;
    if (seq > 0) {
        for (;;) {
            uint64_t min_ack = UINT64_MAX;
            for (uint64_t k = 0; k < num_readers; ++k) {
                const uint64_t a =
                    slot(base, 24 + 8 * k)->load(std::memory_order_acquire);
                if (a < min_ack) min_ack = a;
            }
            if (min_ack >= seq) break;
            if (timeout_us >= 0 && now_us() > deadline) return -1;
            w.wait();
        }
    }
    if (len > 0) std::memcpy(base + header, data, len);
    slot(base, 8)->store(len, std::memory_order_relaxed);
    slot(base, 16)->store(flag, std::memory_order_relaxed);
    seq_slot->store(seq + 1, std::memory_order_release);  // publish
    return 0;
}

// Block until a message newer than last_seq is published; reports its
// length + flag (payload stays in shm — the caller slices it zero-copy).
// Returns 0 ok, -1 timeout.
int64_t rtpu_ch_wait_read(uint8_t* base, uint64_t last_seq,
                          uint64_t* out_len, uint64_t* out_flag,
                          int64_t timeout_us) {
    auto* seq_slot = slot(base, 0);
    const uint64_t deadline = timeout_us < 0 ? 0 : now_us() + timeout_us;
    Waiter w;
    while (seq_slot->load(std::memory_order_acquire) <= last_seq) {
        if (timeout_us >= 0 && now_us() > deadline) return -1;
        w.wait();
    }
    *out_len = slot(base, 8)->load(std::memory_order_relaxed);
    *out_flag = slot(base, 16)->load(std::memory_order_relaxed);
    return 0;
}

// Idempotent absolute ack into this reader's own slot.
void rtpu_ch_ack(uint8_t* base, uint64_t reader_slot_idx, uint64_t seq) {
    slot(base, 24 + 8 * reader_slot_idx)->store(seq, std::memory_order_release);
}

}  // extern "C"
