// Thread/race stress harness for the native components, built for TSAN.
//
// Reference analog: the `build:tsan` bazel config (`.bazelrc:103-110`) that
// runs the C++ core's tests under ThreadSanitizer — the one place data races
// in process-shared structures actually matter here is arena.cpp's allocator
// + channel.cpp's seqlock.
//
// Build & run (scripts/tsan_native.sh):
//   g++ -fsanitize=thread -O1 -g -std=c++17 native_stress_test.cpp \
//       arena.cpp channel.cpp -lpthread -lrt -o /tmp/native_tsan && /tmp/native_tsan
//
// Exit code 0 + no TSAN report = pass.

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

#include <sys/socket.h>
#include <unistd.h>

// ---- arena C API (arena.cpp) ----
extern "C" {
void* rt_arena_create(const char* name, uint64_t capacity, uint64_t flags);
void* rt_arena_attach(const char* name);
int64_t rt_arena_alloc(void* h, const char* id, uint64_t size);
int rt_arena_seal(void* h, const char* id);
int64_t rt_arena_get(void* h, const char* id, uint64_t* size_out);
int rt_arena_release(void* h, const char* id);
int rt_arena_delete(void* h, const char* id);
int rt_arena_detach(void* h);
int rt_arena_unlink(const char* name);

int64_t rtpu_ch_write(uint8_t* base, uint64_t num_readers, const uint8_t* data,
                      uint64_t len, uint64_t flag, int64_t timeout_us);
int64_t rtpu_ch_wait_read(uint8_t* base, uint64_t last_seq, uint64_t* out_len,
                          uint64_t* out_flag, int64_t timeout_us);
void rtpu_ch_ack(uint8_t* base, uint64_t reader_slot_idx, uint64_t seq);

// ---- bulk lander C API (bulk.cpp) ----
long long rt_bulk_land_stream(int sock_fd, int dst_fd, unsigned long long off,
                              unsigned long long len, int deadline_ms);
void* rt_lander_create(int dst_fd, int depth);
long long rt_lander_submit(void* h, const void* buf, unsigned long long off,
                           unsigned long long len, int timeout_ms);
int rt_lander_wait(void* h, unsigned long long target, int timeout_ms);
long long rt_lander_completed(void* h);
int rt_lander_error(void* h);
int rt_lander_close(void* h, int timeout_ms);
}

static std::atomic<int> failures{0};

#define CHECK(cond, msg)                                   \
    do {                                                   \
        if (!(cond)) {                                     \
            std::fprintf(stderr, "FAIL: %s\n", msg);       \
            failures.fetch_add(1);                         \
        }                                                  \
    } while (0)

// ------------------------------------------------------------------ arena
static void arena_stress() {
    const char* NAME = "tsan-arena-test";
    rt_arena_unlink(NAME);
    void* h = rt_arena_create(NAME, 8ull << 20, 0);
    CHECK(h != nullptr, "arena create");

    constexpr int kThreads = 8;
    constexpr int kOps = 300;
    std::vector<std::thread> ts;
    for (int t = 0; t < kThreads; ++t) {
        ts.emplace_back([&, t] {
            // Each thread attaches its own handle — like separate processes.
            void* hh = rt_arena_attach(NAME);
            CHECK(hh != nullptr, "arena attach");
            for (int i = 0; i < kOps; ++i) {
                char id[64];
                std::snprintf(id, sizeof id, "obj-%d-%d", t, i);
                int64_t off = rt_arena_alloc(hh, id, 512);
                if (off < 0) continue;  // arena full under churn — fine
                CHECK(rt_arena_seal(hh, id) == 0, "seal");
                uint64_t size = 0;
                CHECK(rt_arena_get(hh, id, &size) >= 0 && size == 512, "get");
                rt_arena_release(hh, id);
                if (i % 3 == 0) rt_arena_delete(hh, id);
            }
            rt_arena_detach(hh);
        });
    }
    for (auto& th : ts) th.join();
    rt_arena_detach(h);
    rt_arena_unlink(NAME);
}

// ---------------------------------------------------------------- channel
static void channel_stress() {
    constexpr uint64_t kReaders = 3;
    constexpr uint64_t kHeader = 24 + 8 * kReaders;
    constexpr uint64_t kCap = kHeader + 4096;
    constexpr int kMsgs = 5000;
    std::vector<uint8_t> buf(kCap, 0);
    uint8_t* base = buf.data();

    std::vector<std::thread> readers;
    for (uint64_t r = 0; r < kReaders; ++r) {
        readers.emplace_back([&, r] {
            uint64_t last = 0;
            for (;;) {
                uint64_t len = 0, flag = 0;
                int64_t rc = rtpu_ch_wait_read(base, last, &len, &flag,
                                               10 * 1000 * 1000);
                CHECK(rc == 0, "reader timeout");
                if (rc != 0) return;
                ++last;
                if (flag == 1) {  // stop sentinel
                    rtpu_ch_ack(base, r, last);
                    return;
                }
                // Payload integrity: all bytes must equal (seq & 0xff) —
                // a torn read under a racing writer would mix values.
                uint8_t expect = static_cast<uint8_t>(last & 0xff);
                const uint8_t* payload = base + kHeader;
                bool ok = len == 128;
                for (uint64_t i = 0; ok && i < len; ++i)
                    ok = payload[i] == expect;
                CHECK(ok, "torn channel payload");
                rtpu_ch_ack(base, r, last);
            }
        });
    }

    uint8_t msg[128];
    for (int i = 1; i <= kMsgs; ++i) {
        std::memset(msg, i & 0xff, sizeof msg);
        int64_t rc = rtpu_ch_write(base, kReaders, msg, sizeof msg, 0,
                                   10 * 1000 * 1000);
        CHECK(rc == 0, "writer timeout");
    }
    rtpu_ch_write(base, kReaders, nullptr, 0, 1, 10 * 1000 * 1000);
    for (auto& th : readers) th.join();
}

// ------------------------------------------------------------ bulk lander
static void lander_stress() {
    // Ring lander: this thread plays the Python reader (fill + submit with
    // the window bound), the native thread pwrites — the exact interleaving
    // core/bulk.py::_land_ring_native runs, minus the socket.
    char tmpl[] = "/tmp/tsan-lander-XXXXXX";
    int fd = mkstemp(tmpl);
    CHECK(fd >= 0, "lander tmpfile");
    unlink(tmpl);
    constexpr int kDepth = 4;
    constexpr int kChunk = 64 << 10;
    constexpr int kChunks = 256;
    void* h = rt_lander_create(fd, kDepth);
    CHECK(h != nullptr, "lander create");
    std::vector<std::vector<char>> bufs(kDepth, std::vector<char>(kChunk));
    for (int i = 0; i < kChunks; ++i) {
        int slot = i % kDepth;
        if (i >= kDepth)  // recycle a slot only after its chunk landed
            CHECK(rt_lander_wait(h, (unsigned long long)(i - kDepth + 1),
                                 10000) == 0, "lander window wait");
        std::memset(bufs[slot].data(), i & 0xff, kChunk);
        CHECK(rt_lander_submit(h, bufs[slot].data(),
                               (unsigned long long)i * kChunk, kChunk,
                               10000) > 0, "lander submit");
    }
    CHECK(rt_lander_wait(h, kChunks, 10000) == 0, "lander drain");
    CHECK(rt_lander_error(h) == 0, "lander error");
    CHECK(rt_lander_completed(h) == kChunks, "lander completed count");
    CHECK(rt_lander_close(h, 10000) == 0, "lander close");
    std::vector<char> back(kChunk);
    for (int i = 0; i < kChunks; ++i) {
        ssize_t n = pread(fd, back.data(), kChunk, (off_t)i * kChunk);
        bool ok = n == kChunk;
        for (int j = 0; ok && j < kChunk; ++j)
            ok = back[j] == (char)(i & 0xff);
        CHECK(ok, "lander landed content");
    }

    // Stream lander over a socketpair: writer thread feeds a pattern, the
    // poll/read/pwrite loop lands it at an offset.
    int sp[2];
    CHECK(socketpair(AF_UNIX, SOCK_STREAM, 0, sp) == 0, "socketpair");
    constexpr long long kStream = 2 << 20;
    std::thread writer([&] {
        std::vector<char> chunk(4096);
        long long sent = 0;
        while (sent < kStream) {
            std::memset(chunk.data(), (sent / 4096) & 0xff, chunk.size());
            ssize_t n = write(sp[1], chunk.data(), chunk.size());
            if (n <= 0) break;
            sent += n;
        }
        close(sp[1]);
    });
    long long rc = rt_bulk_land_stream(sp[0], fd, 0, kStream, 10000);
    CHECK(rc == kStream, "stream land");
    writer.join();
    close(sp[0]);
    for (int i = 0; i < (int)(kStream / 4096); ++i) {
        char b = 0;
        CHECK(pread(fd, &b, 1, (off_t)i * 4096) == 1 && b == (char)(i & 0xff),
              "stream landed content");
    }
    close(fd);
}

int main() {
    arena_stress();
    channel_stress();
    lander_stress();
    if (failures.load() != 0) {
        std::fprintf(stderr, "%d failures\n", failures.load());
        return 1;
    }
    std::printf("native stress OK\n");
    return 0;
}
