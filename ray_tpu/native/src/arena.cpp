// Shared-memory object arena — the native core of PlasmaLite.
//
// Reference analog: the plasma store (`src/ray/object_manager/plasma/store.h:55`,
// `PlasmaAllocator` + `dlmalloc.cc` over shm). Redesign for one machine:
// instead of a store *server* brokered over a unix socket with fd-passing
// (`fling.cc`), every process maps ONE session arena segment directly; a
// process-shared robust mutex guards the allocator + object index, and
// sealed-object reads are zero-copy pointers into the mapping. No RPC on the
// object hot path at all.
//
// Layout:  [ArenaHeader | index slots | data region]
//   data region: first-fit free list with offset-sorted coalescing.
//   index: open-addressing (linear probe) table keyed by the object hex id.
//
// C ABI at the bottom — consumed by ray_tpu/native/__init__.py via ctypes.

#include <cstdint>
#include <cstring>
#include <cerrno>
#include <cstdio>

#include <fcntl.h>
#include <pthread.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t kMagic = 0x52545055'41524E41ull;  // "RTPUARNA"
constexpr uint64_t kVersion = 1;
constexpr uint64_t kIdBytes = 64;     // hex ids (<= 56 chars) + NUL
constexpr uint64_t kNoOffset = ~0ull;
constexpr uint64_t kMinSplit = 128;   // don't split blocks smaller than this

struct IndexEntry {
  char id[kIdBytes];
  uint64_t offset;    // into data region (payload, past BlockHeader)
  uint64_t size;      // payload size
  uint32_t refcount;
  uint32_t flags;     // 1 = used, 2 = sealed, 4 = tombstone
  uint64_t lru;
};

struct BlockHeader {
  uint64_t size;       // payload capacity of this block
  uint64_t next_free;  // offset of next free block (when on the free list)
};

struct ArenaHeader {
  uint64_t magic;
  uint64_t version;
  uint64_t capacity;      // data region bytes
  uint64_t data_offset;   // from segment base
  uint64_t index_slots;
  uint64_t index_offset;  // from segment base
  uint64_t free_head;     // offset of first free block in data region
  uint64_t used_bytes;
  uint64_t lru_clock;
  uint64_t num_objects;
  pthread_mutex_t mutex;
};

struct Handle {
  uint8_t* base;
  uint64_t map_size;
  ArenaHeader* hdr;
  bool owner;
  char name[256];
};

inline IndexEntry* index_at(Handle* h, uint64_t slot) {
  return reinterpret_cast<IndexEntry*>(h->base + h->hdr->index_offset) + slot;
}

inline uint8_t* data_base(Handle* h) { return h->base + h->hdr->data_offset; }

inline BlockHeader* block_at(Handle* h, uint64_t off) {
  return reinterpret_cast<BlockHeader*>(data_base(h) + off);
}

uint64_t fnv1a(const char* s) {
  uint64_t x = 1469598103934665603ull;
  for (; *s; ++s) {
    x ^= static_cast<uint64_t>(static_cast<unsigned char>(*s));
    x *= 1099511628211ull;
  }
  return x;
}

class Locker {
 public:
  explicit Locker(Handle* h) : h_(h) {
    int rc = pthread_mutex_lock(&h_->hdr->mutex);
    if (rc == EOWNERDEAD) {
      // A process died holding the lock; the state it guarded is still
      // structurally valid (all mutations are ordered to keep it so).
      pthread_mutex_consistent(&h_->hdr->mutex);
    }
  }
  ~Locker() { pthread_mutex_unlock(&h_->hdr->mutex); }

 private:
  Handle* h_;
};

// Find entry slot for id; returns slot or ~0. If `for_insert`, returns the
// first usable (free/tombstone) slot when the id is absent.
uint64_t find_slot(Handle* h, const char* id, bool for_insert) {
  const uint64_t n = h->hdr->index_slots;
  uint64_t slot = fnv1a(id) % n;
  uint64_t first_tomb = kNoOffset;
  for (uint64_t probe = 0; probe < n; ++probe, slot = (slot + 1) % n) {
    IndexEntry* e = index_at(h, slot);
    if (e->flags & 1) {
      if (std::strncmp(e->id, id, kIdBytes) == 0) return slot;
    } else if (e->flags & 4) {
      if (for_insert && first_tomb == kNoOffset) first_tomb = slot;
    } else {
      // Truly empty: id is not in the table.
      if (!for_insert) return kNoOffset;
      return first_tomb != kNoOffset ? first_tomb : slot;
    }
  }
  return for_insert ? first_tomb : kNoOffset;
}

// Allocate a data block (first fit). Returns payload offset or kNoOffset.
uint64_t alloc_block(Handle* h, uint64_t payload) {
  payload = (payload + 7) & ~7ull;  // 8-byte align
  ArenaHeader* a = h->hdr;
  uint64_t prev = kNoOffset;
  uint64_t cur = a->free_head;
  while (cur != kNoOffset) {
    BlockHeader* b = block_at(h, cur);
    if (b->size >= payload) {
      uint64_t remainder = b->size - payload;
      uint64_t next = b->next_free;
      if (remainder >= sizeof(BlockHeader) + kMinSplit) {
        // Split: tail becomes a new free block.
        uint64_t tail_off = cur + sizeof(BlockHeader) + payload;
        BlockHeader* tail = block_at(h, tail_off);
        tail->size = remainder - sizeof(BlockHeader);
        tail->next_free = next;
        b->size = payload;
        next = tail_off;
      }
      if (prev == kNoOffset) a->free_head = next;
      else block_at(h, prev)->next_free = next;
      a->used_bytes += b->size + sizeof(BlockHeader);
      return cur + sizeof(BlockHeader);
    }
    prev = cur;
    cur = b->next_free;
  }
  return kNoOffset;
}

// Return a payload offset's block to the free list (sorted by offset,
// coalescing with both neighbors).
void free_block(Handle* h, uint64_t payload_off) {
  ArenaHeader* a = h->hdr;
  uint64_t blk = payload_off - sizeof(BlockHeader);
  BlockHeader* b = block_at(h, blk);
  a->used_bytes -= b->size + sizeof(BlockHeader);

  uint64_t prev = kNoOffset;
  uint64_t cur = a->free_head;
  while (cur != kNoOffset && cur < blk) {
    prev = cur;
    cur = block_at(h, cur)->next_free;
  }
  // Insert between prev and cur.
  b->next_free = cur;
  if (prev == kNoOffset) a->free_head = blk;
  else block_at(h, prev)->next_free = blk;
  // Coalesce with next.
  if (cur != kNoOffset && blk + sizeof(BlockHeader) + b->size == cur) {
    BlockHeader* nb = block_at(h, cur);
    b->size += sizeof(BlockHeader) + nb->size;
    b->next_free = nb->next_free;
  }
  // Coalesce with prev.
  if (prev != kNoOffset) {
    BlockHeader* pb = block_at(h, prev);
    if (prev + sizeof(BlockHeader) + pb->size == blk) {
      pb->size += sizeof(BlockHeader) + b->size;
      pb->next_free = b->next_free;
    }
  }
}

Handle* map_segment(const char* name, uint64_t map_size, bool owner, int fd) {
  void* mem = mmap(nullptr, map_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return nullptr;
  Handle* h = new Handle();
  h->base = static_cast<uint8_t*>(mem);
  h->map_size = map_size;
  h->hdr = reinterpret_cast<ArenaHeader*>(mem);
  h->owner = owner;
  std::snprintf(h->name, sizeof(h->name), "%s", name);
  return h;
}

}  // namespace

extern "C" {

void* rt_arena_create(const char* name, uint64_t capacity, uint64_t index_slots) {
  if (index_slots == 0) {
    index_slots = capacity / 65536;
    if (index_slots < 1024) index_slots = 1024;
    if (index_slots > (1u << 20)) index_slots = 1u << 20;
  }
  uint64_t index_bytes = index_slots * sizeof(IndexEntry);
  uint64_t header_bytes = (sizeof(ArenaHeader) + 63) & ~63ull;
  uint64_t map_size = header_bytes + index_bytes + capacity;

  shm_unlink(name);  // replace any stale segment from a dead session
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  if (ftruncate(fd, static_cast<off_t>(map_size)) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  Handle* h = map_segment(name, map_size, /*owner=*/true, fd);
  if (!h) {
    shm_unlink(name);
    return nullptr;
  }
  ArenaHeader* a = h->hdr;
  std::memset(a, 0, header_bytes + index_bytes);
  a->version = kVersion;
  a->capacity = capacity;
  a->data_offset = header_bytes + index_bytes;
  a->index_slots = index_slots;
  a->index_offset = header_bytes;
  a->used_bytes = 0;
  a->lru_clock = 0;
  a->num_objects = 0;

  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&a->mutex, &attr);
  pthread_mutexattr_destroy(&attr);

  // One free block spanning the data region.
  BlockHeader* first = block_at(h, 0);
  first->size = capacity - sizeof(BlockHeader);
  first->next_free = kNoOffset;
  a->free_head = 0;

  __sync_synchronize();
  a->magic = kMagic;  // publish: attachers spin on this
  return h;
}

void* rt_arena_attach(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < static_cast<off_t>(sizeof(ArenaHeader))) {
    close(fd);
    return nullptr;
  }
  Handle* h = map_segment(name, static_cast<uint64_t>(st.st_size), false, fd);
  if (!h) return nullptr;
  if (h->hdr->magic != kMagic || h->hdr->version != kVersion) {
    munmap(h->base, h->map_size);
    delete h;
    return nullptr;
  }
  return h;
}

// Allocate + register an object. Returns payload offset or -1 (full / exists).
int64_t rt_arena_alloc(void* hv, const char* id, uint64_t size) {
  Handle* h = static_cast<Handle*>(hv);
  Locker lock(h);
  uint64_t slot = find_slot(h, id, /*for_insert=*/false);
  if (slot != kNoOffset) return -1;  // already present
  slot = find_slot(h, id, /*for_insert=*/true);
  if (slot == kNoOffset) return -1;  // index full
  uint64_t off = alloc_block(h, size);
  if (off == kNoOffset) return -1;   // arena full
  IndexEntry* e = index_at(h, slot);
  std::memset(e, 0, sizeof(*e));
  std::snprintf(e->id, kIdBytes, "%s", id);
  e->offset = off;
  e->size = size;
  e->refcount = 0;
  e->flags = 1;  // used, unsealed
  e->lru = ++h->hdr->lru_clock;
  h->hdr->num_objects++;
  return static_cast<int64_t>(off);
}

int rt_arena_seal(void* hv, const char* id) {
  Handle* h = static_cast<Handle*>(hv);
  Locker lock(h);
  uint64_t slot = find_slot(h, id, false);
  if (slot == kNoOffset) return -1;
  index_at(h, slot)->flags |= 2;
  return 0;
}

// Pin + locate a sealed object. Returns payload offset or -1; size out-param.
int64_t rt_arena_get(void* hv, const char* id, uint64_t* size_out) {
  Handle* h = static_cast<Handle*>(hv);
  Locker lock(h);
  uint64_t slot = find_slot(h, id, false);
  if (slot == kNoOffset) return -1;
  IndexEntry* e = index_at(h, slot);
  if (!(e->flags & 2)) return -2;  // not sealed yet
  e->refcount++;
  e->lru = ++h->hdr->lru_clock;
  if (size_out) *size_out = e->size;
  return static_cast<int64_t>(e->offset);
}

int rt_arena_release(void* hv, const char* id) {
  Handle* h = static_cast<Handle*>(hv);
  Locker lock(h);
  uint64_t slot = find_slot(h, id, false);
  if (slot == kNoOffset) return -1;
  IndexEntry* e = index_at(h, slot);
  if (e->refcount > 0) e->refcount--;
  return 0;
}

// Remove an object (controller-directed). Fails if pinned.
int rt_arena_delete(void* hv, const char* id) {
  Handle* h = static_cast<Handle*>(hv);
  Locker lock(h);
  uint64_t slot = find_slot(h, id, false);
  if (slot == kNoOffset) return -1;
  IndexEntry* e = index_at(h, slot);
  if (e->refcount > 0) return -2;
  free_block(h, e->offset);
  e->flags = 4;  // tombstone keeps probe chains intact
  h->hdr->num_objects--;
  return 0;
}

// Evict up to `want_bytes` of sealed, unpinned objects (LRU order).
// Returns bytes reclaimed. Evicted ids are written into `out_ids`
// (out_cap slots of 64 bytes each) so the caller can inform its control
// plane; count written to out_count.
uint64_t rt_arena_evict_lru(void* hv, uint64_t want_bytes, char* out_ids,
                            uint64_t out_cap, uint64_t* out_count) {
  Handle* h = static_cast<Handle*>(hv);
  Locker lock(h);
  uint64_t reclaimed = 0, count = 0;
  while (reclaimed < want_bytes) {
    uint64_t best = kNoOffset, best_lru = ~0ull;
    for (uint64_t s = 0; s < h->hdr->index_slots; ++s) {
      IndexEntry* e = index_at(h, s);
      if ((e->flags & 1) && (e->flags & 2) && e->refcount == 0 && e->lru < best_lru) {
        best = s;
        best_lru = e->lru;
      }
    }
    if (best == kNoOffset) break;
    IndexEntry* e = index_at(h, best);
    if (count < out_cap && out_ids) {
      std::memcpy(out_ids + count * kIdBytes, e->id, kIdBytes);
    }
    reclaimed += e->size;
    free_block(h, e->offset);
    e->flags = 4;
    h->hdr->num_objects--;
    count++;
  }
  if (out_count) *out_count = count;
  return reclaimed;
}

uint8_t* rt_arena_base(void* hv) {
  Handle* h = static_cast<Handle*>(hv);
  return data_base(h);
}

uint64_t rt_arena_capacity(void* hv) { return static_cast<Handle*>(hv)->hdr->capacity; }
// Payload base as a FILE offset: object offsets from rt_arena_get/alloc are
// relative to this (the bulk plane sendfiles spans of the backing file).
uint64_t rt_arena_data_offset(void* hv) { return static_cast<Handle*>(hv)->hdr->data_offset; }
uint64_t rt_arena_used(void* hv) { return static_cast<Handle*>(hv)->hdr->used_bytes; }
uint64_t rt_arena_num_objects(void* hv) { return static_cast<Handle*>(hv)->hdr->num_objects; }

int rt_arena_detach(void* hv) {
  Handle* h = static_cast<Handle*>(hv);
  munmap(h->base, h->map_size);
  delete h;
  return 0;
}

int rt_arena_unlink(const char* name) { return shm_unlink(name); }

}  // extern "C"
