// Native bulk-plane landing — the receive half of core/bulk.py off the GIL.
//
// Why this exists: the pipelined Python landing (ChunkPipeline) interleaves a
// reader thread (recv_into) and lander thread(s) (pwrite) through the GIL. On
// CPU-starved hosts the GIL handoff per chunk serializes what the window was
// built to overlap — the in-cluster 8 GiB pull measured 0.68-0.74 GiB/s while
// the same syscalls issued from ONE native loop measure 1.1+ (see
// docs/ROOFLINE_put_path.md "in-cluster host scheduling" section). Two paths:
//
//  1. rt_bulk_land_stream — the whole span lands in one native call: a
//     poll/read/pwrite loop between the socket fd and the destination file at
//     its offset. The payload never passes through Python; ctypes releases
//     the GIL for the duration. Per-iteration poll() enforces the same
//     PROGRESS deadline `transfer_chunk_timeout_s` gives the Python path
//     (any byte of progress re-arms it).
//
//  2. rt_lander_* — a pinned lander thread consuming (buf, dst_off, len)
//     descriptors from a bounded SPSC ring: Python keeps doing the recv_into
//     (released GIL, deep rcvbuf) while the landing pwrites run entirely
//     native. For hosts with spare cores this preserves the recv/land
//     overlap WITHOUT a Python lander thread in the GIL rotation. Completion
//     is strictly FIFO (single consumer), so the Python side can recycle
//     chunk buffers by watermark. Synchronization is atomics + an adaptive
//     yield/sleep waiter (channel.cpp idiom) — no mutex/condvar, which also
//     keeps the TSAN harness (native_stress_test.cpp) clean of libstdc++
//     condition_variable interception artifacts. At 8-32 MiB chunk
//     granularity the 100µs sleep quantum is noise.
//
// Failure semantics mirror core/bulk.py exactly (chaos-tested there): a
// stalled peer -> -ETIMEDOUT within the progress deadline; a peer closing
// mid-span -> -EPIPE; a landing write error -> its -errno. The caller aborts
// its writer, so no partial object becomes visible. rt_lander_close() poisons
// the ring; if the lander is STUCK inside a pwrite past the deadline the
// handle and thread are deliberately leaked (return 1) — the Python side then
// leaks the chunk buffers too, because freeing memory a kernel call may still
// land into would be a use-after-free (same contract as the Python
// pipeline's stuck-lander abort).

#include <atomic>
#include <cerrno>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <thread>
#include <vector>

#include <poll.h>
#include <sched.h>
#include <unistd.h>

namespace {

// One read()'s worth of staging for the stream path. 4 MiB keeps the
// buffer cache-adjacent while costing only ~2k syscall pairs per 8 GiB.
constexpr size_t kStreamBuf = 4 << 20;

inline uint64_t now_ms() {
    timespec ts;
    clock_gettime(CLOCK_MONOTONIC, &ts);
    return static_cast<uint64_t>(ts.tv_sec) * 1000ull + ts.tv_nsec / 1000000;
}

// Adaptive waiter for chunk-granularity events: a few yields, then 100µs
// sleeps (channel.cpp's Waiter, minus the spin phase — chunk landings are
// milliseconds apart, so burning pause-spins would only steal the sibling
// thread's cycles on small hosts).
struct Waiter {
    uint64_t rounds = 0;
    void wait() {
        if (rounds < 64) {
            sched_yield();
        } else {
            timespec ts{0, 100000};  // 100µs
            nanosleep(&ts, nullptr);
        }
        ++rounds;
    }
};

int pwrite_full(int fd, const char* buf, size_t len, uint64_t off) {
    size_t done = 0;
    while (done < len) {
        ssize_t m = pwrite(fd, buf + done, len - done, (off_t)(off + done));
        if (m < 0) {
            if (errno == EINTR) continue;
            return -errno;
        }
        if (m == 0) return -EIO;
        done += (size_t)m;
    }
    return 0;
}

}  // namespace

extern "C" {

// Land `len` bytes from `sock_fd` into `dst_fd` at `dst_off`. Returns bytes
// landed (== len) on success, negative errno on failure:
//   -ETIMEDOUT  no socket progress within deadline_ms (stalled peer)
//   -EPIPE      peer closed mid-span
//   other       read()/pwrite() errno
// Works with the socket in blocking OR non-blocking mode (poll gates reads).
long long rt_bulk_land_stream(int sock_fd, int dst_fd,
                              unsigned long long dst_off,
                              unsigned long long len, int deadline_ms) {
    char* buf = (char*)malloc(kStreamBuf);
    if (buf == nullptr) return -ENOMEM;
    unsigned long long got = 0;
    while (got < len) {
        pollfd pfd{sock_fd, POLLIN, 0};
        int pr = poll(&pfd, 1, deadline_ms);
        if (pr < 0) {
            if (errno == EINTR) continue;
            int e = errno; free(buf); return -e;
        }
        if (pr == 0) { free(buf); return -ETIMEDOUT; }
        size_t want = len - got > kStreamBuf ? kStreamBuf : (size_t)(len - got);
        ssize_t n = read(sock_fd, buf, want);
        if (n < 0) {
            if (errno == EINTR || errno == EAGAIN || errno == EWOULDBLOCK)
                continue;  // spurious readiness; the poll deadline still arms
            int e = errno; free(buf); return -e;
        }
        if (n == 0) { free(buf); return -EPIPE; }
        int w = pwrite_full(dst_fd, buf, (size_t)n, dst_off + got);
        if (w != 0) { free(buf); return w; }
        got += (unsigned long long)n;
    }
    free(buf);
    return (long long)got;
}

// ------------------------------------------------------------- ring lander
// Single-producer (the Python reader) / single-consumer (the lander thread)
// bounded ring. Ring entries are plain data published by the release-store
// of `submitted` and consumed before the release-store of `completed`.
struct LandDesc {
    const char* buf;
    uint64_t off;
    uint64_t len;
};

struct Lander {
    int fd;  // dup'd: the caller may close its fd while we still land
    std::vector<LandDesc> ring;
    size_t depth;
    std::atomic<uint64_t> submitted{0};  // accepted into the ring
    std::atomic<uint64_t> completed{0};  // fully landed (FIFO)
    std::atomic<int> err{0};             // first landing errno (sticky)
    std::atomic<bool> poisoned{false};   // abort: skip pending writes
    std::atomic<bool> exited{false};     // lander thread left its loop
    std::thread th;
};

static void lander_loop(Lander* L) {
    Waiter w;
    for (;;) {
        uint64_t done = L->completed.load(std::memory_order_relaxed);
        while (L->submitted.load(std::memory_order_acquire) == done) {
            if (L->poisoned.load(std::memory_order_acquire)) {
                L->exited.store(true, std::memory_order_release);
                return;
            }
            w.wait();
        }
        w.rounds = 0;
        LandDesc d = L->ring[done % L->depth];
        // After the first error (or a poison) only drain bookkeeping — the
        // transfer is aborting and the buffers may be recycled/freed.
        if (L->err.load(std::memory_order_relaxed) == 0 &&
            !L->poisoned.load(std::memory_order_acquire)) {
            int rc = pwrite_full(L->fd, d.buf, (size_t)d.len, d.off);
            if (rc != 0) {
                int expect = 0;
                L->err.compare_exchange_strong(expect, -rc);
            }
        }
        L->completed.store(done + 1, std::memory_order_release);
    }
}

void* rt_lander_create(int dst_fd, int depth) {
    if (depth < 1) depth = 1;
    int fd = dup(dst_fd);
    if (fd < 0) return nullptr;
    Lander* L = new Lander();
    L->fd = fd;
    L->depth = (size_t)depth;
    L->ring.resize(L->depth);
    L->th = std::thread(lander_loop, L);
    return L;
}

// Queue one filled chunk. Blocks while the ring is full (bounded window).
// Returns the 1-based submission count, -ETIMEDOUT if no slot freed within
// timeout_ms (stalled landing), or the sticky landing error as -errno.
long long rt_lander_submit(void* h, const void* buf,
                           unsigned long long dst_off, unsigned long long len,
                           int timeout_ms) {
    Lander* L = (Lander*)h;
    if (L->poisoned.load(std::memory_order_acquire)) return -EINVAL;
    const uint64_t deadline = now_ms() + (uint64_t)(timeout_ms > 0 ? timeout_ms : 0);
    Waiter w;
    uint64_t sub = L->submitted.load(std::memory_order_relaxed);
    while (sub - L->completed.load(std::memory_order_acquire) >= L->depth) {
        int e = L->err.load(std::memory_order_relaxed);
        if (e != 0) return -(long long)e;
        if (now_ms() > deadline) return -ETIMEDOUT;
        w.wait();
    }
    int e = L->err.load(std::memory_order_relaxed);
    if (e != 0) return -(long long)e;
    L->ring[sub % L->depth] = LandDesc{(const char*)buf, dst_off, len};
    L->submitted.store(sub + 1, std::memory_order_release);
    return (long long)(sub + 1);
}

// Wait until at least `target` chunks have landed. 0 ok, -ETIMEDOUT, or the
// sticky landing error as -errno.
int rt_lander_wait(void* h, unsigned long long target, int timeout_ms) {
    Lander* L = (Lander*)h;
    const uint64_t deadline = now_ms() + (uint64_t)(timeout_ms > 0 ? timeout_ms : 0);
    Waiter w;
    while (L->completed.load(std::memory_order_acquire) < target) {
        int e = L->err.load(std::memory_order_relaxed);
        if (e != 0) return -e;
        if (now_ms() > deadline) return -ETIMEDOUT;
        w.wait();
    }
    int e = L->err.load(std::memory_order_relaxed);
    return e != 0 ? -e : 0;
}

long long rt_lander_completed(void* h) {
    Lander* L = (Lander*)h;
    return (long long)L->completed.load(std::memory_order_acquire);
}

int rt_lander_error(void* h) {
    Lander* L = (Lander*)h;
    return L->err.load(std::memory_order_acquire);
}

// Poison and join. Pending un-landed chunks are SKIPPED (close never
// flushes — drain with rt_lander_wait first). Returns 0 when the lander
// exited (handle freed) or 1 when it is stuck past timeout_ms: the thread is
// detached and the handle LEAKED on purpose — it may still be inside a
// pwrite from a submitted buffer, so the caller must keep those buffers
// alive forever (the Python side parks them in a module-level leak list,
// mirroring the Python pipeline's stuck-lander contract).
int rt_lander_close(void* h, int timeout_ms) {
    Lander* L = (Lander*)h;
    L->poisoned.store(true, std::memory_order_release);
    const uint64_t deadline = now_ms() + (uint64_t)(timeout_ms > 0 ? timeout_ms : 0);
    Waiter w;
    while (!L->exited.load(std::memory_order_acquire)) {
        if (now_ms() > deadline) {
            L->th.detach();
            return 1;
        }
        w.wait();
    }
    L->th.join();
    close(L->fd);
    delete L;
    return 0;
}

}  // extern "C"
