"""Native (C++) components, built on demand with g++ and bound via ctypes.

The reference keeps its runtime core in C++ (`src/ray/…`); here the
machine-local object plane's hot allocator lives in `src/arena.cpp`
(plasma-equivalent arena — SURVEY.md §2.1), the seqlock channel ops in
`src/channel.cpp`, and the bulk-plane off-GIL landing (stream + ring
landers) in `src/bulk.cpp`. Each .so is compiled once per source change
into `_build/` (no pip, no pybind — plain g++ + ctypes per the
environment contract); every loader degrades to None so the Python
fallbacks keep working where no toolchain exists.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "src", "arena.cpp")
_BUILD_DIR = os.path.join(_DIR, "_build")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_build_error: Optional[str] = None


def _lib_path(src: str, stem: str) -> str:
    """Content-addressed output path: staleness keyed on the source hash,
    never mtime (a fresh clone stamps all files with the same mtime)."""
    with open(src, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:12]
    return os.path.join(_BUILD_DIR, f"lib{stem}-{digest}.so")


def _compile(src: str, lib_path: str, what: str) -> Optional[str]:
    """Compile one .so if absent. Returns an error string or None."""
    if os.path.exists(lib_path):
        return None
    os.makedirs(_BUILD_DIR, exist_ok=True)
    tmp = lib_path + f".tmp.{os.getpid()}"
    cmd = [
        "g++", "-O2", "-shared", "-fPIC", "-std=c++17",
        "-o", tmp, src, "-lpthread", "-lrt",
    ]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:  # g++ absent/hung
        return f"{what} build failed: {e!r}"
    if proc.returncode != 0:
        return f"{what} build failed:\n{proc.stderr[-2000:]}"
    os.replace(tmp, lib_path)  # atomic: concurrent builders race safely
    # Prune siblings from older source revisions (content-addressed names
    # accumulate otherwise; live processes keep their mmap via the open fd).
    stem = os.path.basename(lib_path).rsplit("-", 1)[0]
    for f in os.listdir(_BUILD_DIR):
        if f.startswith(stem + "-") and f.endswith(".so") and f != os.path.basename(lib_path):
            try:
                os.remove(os.path.join(_BUILD_DIR, f))
            except OSError:
                pass
    return None


def load_arena_lib() -> Optional[ctypes.CDLL]:
    """The cached handle to the native library, or None if unbuildable."""
    global _lib, _build_error
    with _lock:
        if _lib is not None:
            return _lib
        if _build_error is not None:
            return None
        lib_path = _lib_path(_SRC, "ray_tpu_arena")
        err = _compile(_SRC, lib_path, "arena")
        if err is not None:
            _build_error = err
            return None
        lib = _dlopen(_SRC, lib_path, "arena")
        if lib is None:
            _build_error = "arena dlopen failed (see stderr)"
            return None
        lib.rt_arena_create.restype = ctypes.c_void_p
        lib.rt_arena_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64]
        lib.rt_arena_attach.restype = ctypes.c_void_p
        lib.rt_arena_attach.argtypes = [ctypes.c_char_p]
        lib.rt_arena_alloc.restype = ctypes.c_int64
        lib.rt_arena_alloc.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_uint64]
        lib.rt_arena_seal.restype = ctypes.c_int
        lib.rt_arena_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rt_arena_get.restype = ctypes.c_int64
        lib.rt_arena_get.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64)
        ]
        lib.rt_arena_release.restype = ctypes.c_int
        lib.rt_arena_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rt_arena_delete.restype = ctypes.c_int
        lib.rt_arena_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.rt_arena_evict_lru.restype = ctypes.c_uint64
        lib.rt_arena_evict_lru.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p,
            ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.rt_arena_base.restype = ctypes.c_void_p
        lib.rt_arena_base.argtypes = [ctypes.c_void_p]
        for fn in ("rt_arena_capacity", "rt_arena_used", "rt_arena_num_objects",
                   "rt_arena_data_offset"):
            getattr(lib, fn).restype = ctypes.c_uint64
            getattr(lib, fn).argtypes = [ctypes.c_void_p]
        lib.rt_arena_detach.restype = ctypes.c_int
        lib.rt_arena_detach.argtypes = [ctypes.c_void_p]
        lib.rt_arena_unlink.restype = ctypes.c_int
        lib.rt_arena_unlink.argtypes = [ctypes.c_char_p]
        _lib = lib
        return _lib


def _dlopen(src: str, lib_path: str, what: str) -> Optional[ctypes.CDLL]:
    """CDLL with one rebuild retry: a concurrent builder's prune can remove
    this digest's file between the existence check and dlopen (shared
    checkout mid-update) — rebuild from source rather than crash."""
    try:
        return ctypes.CDLL(lib_path)
    except OSError:
        if _compile(src, lib_path, what) is not None:
            return None
        try:
            return ctypes.CDLL(lib_path)
        except OSError:
            return None


def build_error() -> Optional[str]:
    return _build_error


# ------------------------------------------------------- channel (seqlock)
_CH_SRC = os.path.join(_DIR, "src", "channel.cpp")
_ch_lib: Optional[ctypes.CDLL] = None
_ch_error: Optional[str] = None


def load_channel_lib() -> Optional[ctypes.CDLL]:
    """Native seqlock channel ops (`src/channel.cpp`) — used by the
    compiled-DAG/pipeline channels; None if unbuildable (Python fallback)."""
    global _ch_lib, _ch_error
    with _lock:
        if _ch_lib is not None:
            return _ch_lib
        if _ch_error is not None:
            return None
        lib_path = _lib_path(_CH_SRC, "ray_tpu_channel")
        err = _compile(_CH_SRC, lib_path, "channel")
        if err is not None:
            _ch_error = err
            return None
        lib = _dlopen(_CH_SRC, lib_path, "channel")
        if lib is None:
            _ch_error = "channel dlopen failed (see stderr)"
            return None
        lib.rtpu_ch_write.restype = ctypes.c_int64
        lib.rtpu_ch_write.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p,
            ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int64,
        ]
        lib.rtpu_ch_wait_read.restype = ctypes.c_int64
        lib.rtpu_ch_wait_read.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int64,
        ]
        lib.rtpu_ch_ack.restype = None
        lib.rtpu_ch_ack.argtypes = [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64]
        _ch_lib = lib
        return _ch_lib


def channel_build_error() -> Optional[str]:
    return _ch_error


# ------------------------------------------------------- bulk lander (off-GIL)
_BULK_SRC = os.path.join(_DIR, "src", "bulk.cpp")
_bulk_lib: Optional[ctypes.CDLL] = None
_bulk_error: Optional[str] = None


def load_bulk_lib() -> Optional[ctypes.CDLL]:
    """Native bulk-plane landing ops (`src/bulk.cpp`): the whole-span
    poll/read/pwrite stream loop and the pinned ring-lander thread — used by
    `core/bulk.py` to take the receive path off the GIL; None if unbuildable
    (the pure-Python ChunkPipeline remains the fallback)."""
    global _bulk_lib, _bulk_error
    with _lock:
        if _bulk_lib is not None:
            return _bulk_lib
        if _bulk_error is not None:
            return None
        lib_path = _lib_path(_BULK_SRC, "ray_tpu_bulk")
        err = _compile(_BULK_SRC, lib_path, "bulk")
        if err is not None:
            _bulk_error = err
            return None
        lib = _dlopen(_BULK_SRC, lib_path, "bulk")
        if lib is None:
            _bulk_error = "bulk dlopen failed (see stderr)"
            return None
        lib.rt_bulk_land_stream.restype = ctypes.c_longlong
        lib.rt_bulk_land_stream.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_int,
        ]
        lib.rt_lander_create.restype = ctypes.c_void_p
        lib.rt_lander_create.argtypes = [ctypes.c_int, ctypes.c_int]
        lib.rt_lander_submit.restype = ctypes.c_longlong
        lib.rt_lander_submit.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_uint64,
            ctypes.c_uint64, ctypes.c_int,
        ]
        lib.rt_lander_wait.restype = ctypes.c_int
        lib.rt_lander_wait.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int,
        ]
        lib.rt_lander_completed.restype = ctypes.c_longlong
        lib.rt_lander_completed.argtypes = [ctypes.c_void_p]
        lib.rt_lander_error.restype = ctypes.c_int
        lib.rt_lander_error.argtypes = [ctypes.c_void_p]
        lib.rt_lander_close.restype = ctypes.c_int
        lib.rt_lander_close.argtypes = [ctypes.c_void_p, ctypes.c_int]
        _bulk_lib = lib
        return _bulk_lib


def bulk_build_error() -> Optional[str]:
    return _bulk_error


class Arena:
    """Pythonic handle over one shm arena (create or attach)."""

    def __init__(self, name: str, capacity: Optional[int] = None, create: bool = False):
        lib = load_arena_lib()
        if lib is None:
            raise RuntimeError(f"native arena unavailable: {build_error()}")
        self._lib = lib
        self.name = name
        if create:
            if capacity is None:
                raise ValueError("capacity required to create an arena")
            self._h = lib.rt_arena_create(name.encode(), capacity, 0)
        else:
            self._h = lib.rt_arena_attach(name.encode())
        if not self._h:
            raise RuntimeError(
                f"arena {'create' if create else 'attach'} failed for {name!r}"
            )
        self._base = lib.rt_arena_base(self._h)
        self._owner = create
        # Guards the handle's LIFETIME for CROSS-THREAD readers: detach()
        # frees the native handle, so a thread that snapshots self._h and
        # then dereferences it races a concurrent detach into a
        # use-after-free (the store's prefault thread reading used-bytes
        # during a borrow/detach cycle was the observed segfault —
        # core/store.py). used_safe() and detach() share this lock; the
        # other methods stay unlocked by contract — they are only called
        # from the thread that owns the handle's lifetime. Any future
        # background reader must go through the lock like used_safe().
        import threading

        self._hlock = threading.RLock()

    # -------------------------------------------------------------- objects
    def create(self, object_id: str, size: int, with_offset: bool = False):
        """Allocate an unsealed object; returns a writable view of it — or
        (view, file_offset) with ``with_offset`` (the bulk plane's same-host
        map handover sendfiles into that span of the backing file; the offset
        is only knowable here because `locate()` requires a sealed object)."""
        off = self._lib.rt_arena_alloc(self._h, object_id.encode(), size)
        if off < 0:
            raise MemoryError(f"arena alloc failed for {object_id} ({size}B)")
        view = self._view(off, size)
        if with_offset:
            return view, off + self._lib.rt_arena_data_offset(self._h)
        return view

    def seal(self, object_id: str):
        if self._lib.rt_arena_seal(self._h, object_id.encode()) != 0:
            raise KeyError(object_id)

    def get(self, object_id: str) -> Optional[memoryview]:
        """Pin + return a read view of a sealed object; None if absent.
        Balance every successful get with release(). Handle-lifetime safe:
        the bulk server's serve threads call this concurrently with session
        teardown's detach() (see _hlock note above — rt_arena_get on a
        freed handle was a real segfault, observed from
        bulk._serve_map → bulk_map_source during the chaos kill test)."""
        with self._hlock:
            if not self._h:
                return None  # arena detached (session tearing down)
            size = ctypes.c_uint64()
            off = self._lib.rt_arena_get(self._h, object_id.encode(), ctypes.byref(size))
            if off == -1:
                return None
            if off == -2:
                raise BlockingIOError(f"object {object_id} not sealed yet")
            return self._view(off, size.value)

    def locate(self, object_id: str):
        """Pin + return (file_offset, size) of a sealed object within the
        arena's backing file (object offsets are payload-relative; adding
        data_offset makes them file offsets — bulk.py sendfiles from them).
        None if absent. Balance every successful locate with release().
        Handle-lifetime safe like get() — bulk serve threads race detach."""
        with self._hlock:
            if not self._h:
                return None
            size = ctypes.c_uint64()
            off = self._lib.rt_arena_get(self._h, object_id.encode(), ctypes.byref(size))
            if off == -1:
                return None
            if off == -2:
                raise BlockingIOError(f"object {object_id} not sealed yet")
            return off + self._lib.rt_arena_data_offset(self._h), size.value

    def release(self, object_id: str):
        with self._hlock:
            if not self._h:
                return  # arena already detached; the pin died with it
            self._lib.rt_arena_release(self._h, object_id.encode())

    def delete(self, object_id: str) -> bool:
        return self._lib.rt_arena_delete(self._h, object_id.encode()) == 0

    def evict_lru(self, want_bytes: int) -> list:
        """Evict sealed, unpinned objects; returns their ids."""
        cap = 4096
        buf = ctypes.create_string_buffer(cap * 64)
        count = ctypes.c_uint64()
        self._lib.rt_arena_evict_lru(self._h, want_bytes, buf, cap, ctypes.byref(count))
        out = []
        for k in range(count.value):
            raw = buf.raw[k * 64 : (k + 1) * 64]
            out.append(raw.split(b"\0", 1)[0].decode())
        return out

    # --------------------------------------------------------------- stats
    @property
    def capacity(self) -> int:
        return self._lib.rt_arena_capacity(self._h)

    @property
    def used(self) -> int:
        return self._lib.rt_arena_used(self._h)

    @property
    def num_objects(self) -> int:
        return self._lib.rt_arena_num_objects(self._h)

    # ------------------------------------------------------------ internals
    def _view(self, offset: int, size: int) -> memoryview:
        buf = (ctypes.c_char * size).from_address(self._base + offset)
        return memoryview(buf).cast("B")

    def used_safe(self) -> int:
        """used-bytes read that is safe against a concurrent detach()
        (raises RuntimeError once detached — callers like the prefault
        thread treat that as "arena gone, stop")."""
        with self._hlock:
            if not self._h:
                raise RuntimeError("arena detached")
            return self._lib.rt_arena_used(self._h)

    def detach(self):
        with self._hlock:
            if self._h:
                self._lib.rt_arena_detach(self._h)
                self._h = None

    def unlink(self):
        self._lib.rt_arena_unlink(self.name.encode())

    def __del__(self):
        try:
            self.detach()
        except Exception:  # noqa: BLE001
            pass
