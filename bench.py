"""Headline benchmark: GPT training throughput on the local TPU chip.

Prints ONE JSON line:
    {"metric": ..., "value": tokens/s/chip, "unit": ..., "vs_baseline": ...}

vs_baseline = achieved MFU / 0.40 — the north-star target from BASELINE.md
(GPT-J pretraining ≥40% MFU through the Train API). The model here is the
largest GPT-2-family config that trains comfortably on one v5e chip; the
per-chip MFU is the quantity the multi-chip sharding is designed to hold.
"""

from __future__ import annotations

import json
import sys
import time


def peak_flops_per_chip() -> float:
    """bf16 peak for the local chip generation."""
    import jax

    kind = jax.devices()[0].device_kind.lower()
    if "v5 lite" in kind or "v5e" in kind or "v5lite" in kind:
        return 197e12
    if "v6" in kind:
        return 918e12
    if "v4" in kind:
        return 275e12
    return 197e12


def _check_device_reachable(timeout_s: float = 180.0):
    """The axon tunnel can wedge such that backend init blocks forever; a
    hung bench is worse than a failed one — probe attach in a daemon thread
    and exit loudly on timeout."""
    import threading

    result = {}

    def probe():
        try:
            import jax

            result["devices"] = [str(d) for d in jax.devices()]
        except Exception as e:  # noqa: BLE001
            result["error"] = repr(e)

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if t.is_alive():
        print(
            json.dumps(
                {
                    "metric": "gpt2_large_train_tokens_per_sec_per_chip",
                    "value": 0,
                    "unit": "tokens/s/chip",
                    "vs_baseline": 0,
                    "error": f"device attach timed out after {timeout_s}s (tunnel wedged?)",
                }
            )
        )
        raise SystemExit(2)
    if "error" in result:
        print(
            json.dumps(
                {
                    "metric": "gpt2_large_train_tokens_per_sec_per_chip",
                    "value": 0,
                    "unit": "tokens/s/chip",
                    "vs_baseline": 0,
                    "error": result["error"],
                }
            )
        )
        raise SystemExit(2)


def main():
    _check_device_reachable()
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models import gpt2_large, init_params, make_train_step

    import os

    if os.environ.get("RAY_TPU_BENCH_SMALL"):  # logic smoke on CPU
        from ray_tpu.models import GPTConfig

        B, S = 2, 128
        cfg = GPTConfig(
            vocab_size=512, n_layers=2, d_model=128, n_heads=4, d_head=32,
            d_mlp=256, max_seq=S, attn_impl="ref", remat=False,
        )
    else:
        # gpt2_large w/ flash blocks (1024,1024) (r4 sweeps). r5 sweep:
        # bf16 adam moments (mu_dtype) free 1.5 GB of HBM, which unlocks
        # remat_policy="attn" (attention fwd runs ONCE per step — its
        # residuals are saved, the rest of the block remats) at B=13:
        # 0.484 MFU vs r4's 0.459 (B=12, full remat, f32 moments).
        # B=14 regresses (0.464, memory pressure); B=16 fails to compile.
        B, S = 13, 1024
        cfg = gpt2_large(
            max_seq=S, attn_impl="flash", remat=True, remat_policy="attn"
        )

    # Initialize on-device (jit) — host-side random init of 350M params on a
    # 1-core VM costs tens of seconds.
    params = jax.jit(lambda key: init_params(key, cfg))(jax.random.PRNGKey(0))
    # bf16 first moments: half the m-state HBM (and its read-modify-write
    # traffic) for negligible update error — the variance stays f32.
    opt = optax.adamw(3e-4, weight_decay=0.1, mu_dtype=jnp.bfloat16)
    opt_state = opt.init(params)
    step = jax.jit(make_train_step(cfg, opt), donate_argnums=(0,))

    tokens = jax.random.randint(jax.random.PRNGKey(1), (B, S + 1), 0, cfg.vocab_size)
    batch = {"tokens": tokens}

    state = (params, opt_state)
    # Warmup / compile. float() forces a host transfer — under the axon
    # tunnel, block_until_ready alone does not reliably fence execution.
    for _ in range(2):
        state, metrics = step(state, batch)
    _ = float(metrics["loss"])

    n_steps = 10
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, metrics = step(state, batch)
    _ = float(metrics["loss"])
    dt = (time.perf_counter() - t0) / n_steps

    tokens_per_step = B * S
    tok_s = tokens_per_step / dt
    mfu = cfg.flops_per_token(S) * tok_s / peak_flops_per_chip()

    print(
        json.dumps(
            {
                "metric": "gpt2_large_train_tokens_per_sec_per_chip",
                "value": round(tok_s, 1),
                "unit": "tokens/s/chip",
                "vs_baseline": round(mfu / 0.40, 3),
                "extra": {
                    "mfu": round(mfu, 4),
                    "step_ms": round(dt * 1000, 2),
                    "params_m": round(cfg.n_params / 1e6, 1),
                    "batch": B,
                    "seq": S,
                    "final_loss": round(float(metrics["loss"]), 3),
                    "device": str(jax.devices()[0].device_kind),
                },
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
